"""RAG question-answering pipelines.

reference: python/pathway/xpacks/llm/question_answering.py —
``BaseRAGQuestionAnswerer``:314 (``answer_query``:451 retrieve → context →
prompt → LLM; ``summarize_query``:491; ``build_server``/``run_server``),
``AdaptiveRAGQuestionAnswerer``:620 over
``answer_with_geometric_rag_strategy[_from_index]``:97/:162 (geometric
2,4,8,… document escalation), ``DeckRetriever``:736, ``RAGClient``:854.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression
from ...internals.schema import Schema, column_definition
from ...internals.table import Table
from ...internals.thisclass import right
from ...internals.udfs import udf
from ...internals.value import Json
from ._utils import RestClientBase, coerce_str
from .llms import BaseChat, prompt_chat_single_qa
from . import prompts
from .vector_store import (
    InputsQuerySchema,
    RetrieveQuerySchema,
    StatisticsQuerySchema,
    _merge_filters,
)

__all__ = [
    "BaseQuestionAnswerer",
    "SummaryQuestionAnswerer",
    "BaseRAGQuestionAnswerer",
    "AdaptiveRAGQuestionAnswerer",
    "answer_with_geometric_rag_strategy",
    "answer_with_geometric_rag_strategy_from_index",
    "DeckRetriever",
    "RAGClient",
]


class AIResponseType:
    SHORT = "short"
    LONG = "long"


# ---------------------------------------------------------------------------
# abstract surface consumed by QARestServer (reference: question_answering.py
# BaseQuestionAnswerer / SummaryQuestionAnswerer protocols)
# ---------------------------------------------------------------------------


class BaseQuestionAnswerer:
    RetrieveQuerySchema = RetrieveQuerySchema
    StatisticsQuerySchema = StatisticsQuerySchema
    InputsQuerySchema = InputsQuerySchema

    class AnswerQuerySchema(Schema):
        prompt: str
        filters: str | None = column_definition(default_value=None)
        model: str | None = column_definition(default_value=None)
        return_context_docs: bool = column_definition(default_value=False)
        response_type: str = column_definition(default_value=AIResponseType.SHORT)

    def answer_query(self, pw_ai_queries: Table) -> Table: ...

    def retrieve(self, queries: Table) -> Table: ...

    def statistics(self, queries: Table) -> Table: ...

    def list_documents(self, queries: Table) -> Table: ...


class SummaryQuestionAnswerer(BaseQuestionAnswerer):
    class SummarizeQuerySchema(Schema):
        text_list: Json
        model: str | None = column_definition(default_value=None)

    def summarize_query(self, summarize_queries: Table) -> Table: ...


import itertools as _itertools

_qa_seq = _itertools.count()


class BaseRAGQuestionAnswerer(SummaryQuestionAnswerer):
    """reference: question_answering.py:314

    Failure domain: LLM calls run through a circuit breaker
    (``xpacks/llm/_breaker.py``).  Consecutive LLM failures trip it, after
    which ``/v1/pw_ai_answer`` keeps answering with *retrieval-only*
    results (``response: null``, ``"degraded": true``, context docs
    included) instead of 5xx-ing; a half-open probe restores full answers
    once the model heals.
    """

    def __init__(
        self,
        llm: BaseChat,
        indexer,  # VectorStoreServer | DocumentStore
        *,
        default_llm_name: str | None = None,
        short_prompt_template=prompts.prompt_short_qa,
        long_prompt_template=prompts.prompt_qa,
        summarize_template=prompts.prompt_summarize,
        search_topk: int = 6,
        llm_breaker: Any = None,
    ):
        self.llm = llm
        self.indexer = indexer
        self.default_llm_name = default_llm_name or getattr(llm, "model", None)
        self.short_prompt_template = short_prompt_template
        self.long_prompt_template = long_prompt_template
        self.summarize_template = summarize_template
        self.search_topk = search_topk
        self.server: Any = None
        self._pending_endpoints: list = []
        # streamed-answer lazy builds run on worker threads
        # (asyncio.to_thread) — serialize concurrent first requests so
        # two planes (each with its own scheduler) are never built
        self._stream_plane_lock = threading.Lock()
        if llm_breaker is None:
            from ._breaker import CircuitBreaker

            llm_breaker = CircuitBreaker(f"llm-{next(_qa_seq)}")
        self.llm_breaker = llm_breaker

    def _guarded_llm(self):
        """The LLM as a breaker-guarded async UDF: a refused or failed
        call yields ``None`` (→ degraded retrieval-only answer) instead of
        an engine-visible exception."""
        from ...internals.udfs import async_executor, udf

        base = self.llm.async_callable()
        breaker = self.llm_breaker

        @udf(executor=async_executor(), return_type=dt.Optional(dt.STR))
        async def guarded_llm(messages, model: str | None = None):
            import time as _time_mod

            from ...internals.flight_recorder import observe_stage, record_span

            if not breaker.allow():
                return None
            wall0 = _time_mod.time()
            t0 = _time_mod.monotonic()
            try:
                result = await base(messages, model=model)
            except Exception as exc:  # noqa: BLE001 — degrade, don't poison
                breaker.record_failure(exc)
                from ...internals.errors import register_error

                register_error(
                    f"LLM call failed, answer degraded to retrieval-only: "
                    f"{type(exc).__name__}: {exc}",
                    kind="serving",
                    operator="llm",
                )
                dur_ms = (_time_mod.monotonic() - t0) * 1000.0
                record_span(
                    "llm", "llm", wall0, dur_ms,
                    attrs={"model": model, "ok": False},
                )
                # failures observe too — a histogram that only sees the
                # healthy calls hides exactly the timeout tail it exists
                # to expose
                observe_stage("llm", dur_ms)
                return None
            breaker.record_success()
            # LLM latency is usually the answer path's dominant stage:
            # span for trace dumps + pathway_request_stage_ms{stage="llm"}
            dur_ms = (_time_mod.monotonic() - t0) * 1000.0
            record_span(
                "llm", "llm", wall0, dur_ms, attrs={"model": model, "ok": True}
            )
            observe_stage("llm", dur_ms)
            return result

        return guarded_llm

    # -- the 4-select answer pipeline (reference: :451-482) --
    def answer_query(self, pw_ai_queries: Table) -> Table:
        queries = pw_ai_queries.select(
            prompt=pw_ai_queries.prompt,
            filters=pw_ai_queries.filters,
            model=ApplyExpression(
                lambda m: m or self.default_llm_name,
                dt.Optional(dt.STR),
                pw_ai_queries.model,
            ),
            return_context_docs=pw_ai_queries.return_context_docs,
            response_type=pw_ai_queries.response_type,
        )
        retrieve_table = queries.select(
            query=queries.prompt,
            k=ApplyExpression(lambda p: self.search_topk, dt.INT, queries.prompt),
            metadata_filter=queries.filters,
            filepath_globpattern=ApplyExpression(
                lambda p: None, dt.Optional(dt.STR), queries.prompt
            ),
        )
        docs_result = self.indexer.retrieve_query(retrieve_table)
        with_docs = queries.with_universe_of(docs_result).select(
            prompt=queries.prompt,
            model=queries.model,
            return_context_docs=queries.return_context_docs,
            response_type=queries.response_type,
            docs=ApplyExpression(
                lambda r: tuple(
                    d.get("text") if isinstance(d, dict) else d
                    for d in (r.value if isinstance(r, Json) else r or ())
                ),
                dt.List(dt.STR),
                docs_result.result,
            ),
        )

        def pick_template(response_type):
            if response_type == AIResponseType.LONG:
                return self.long_prompt_template
            return self.short_prompt_template

        # both templates are UDFs; response_type is per-row, so build both
        # and pick row-wise (the reference dispatches the same way)
        prompted = with_docs.select(
            prompt_short=self.short_prompt_template(
                with_docs.prompt, with_docs.docs
            ),
            prompt_long=self.long_prompt_template(with_docs.prompt, with_docs.docs),
            response_type=with_docs.response_type,
            model=with_docs.model,
            return_context_docs=with_docs.return_context_docs,
            docs=with_docs.docs,
        )
        chosen = prompted.select(
            rag_prompt=ApplyExpression(
                lambda rt, s, l: l if rt == AIResponseType.LONG else s,
                dt.STR,
                prompted.response_type,
                prompted.prompt_short,
                prompted.prompt_long,
            ),
            model=prompted.model,
            return_context_docs=prompted.return_context_docs,
            docs=prompted.docs,
        )
        answered = chosen.select(
            response=self._guarded_llm()(
                prompt_chat_single_qa(chosen.rag_prompt), model=chosen.model
            ),
            return_context_docs=chosen.return_context_docs,
            docs=chosen.docs,
        )

        def pack(response, return_context_docs, docs) -> Json:
            if response is None:
                # LLM breaker open / call failed: retrieval-only answer
                return Json(
                    {
                        "response": None,
                        "degraded": True,
                        "context_docs": [coerce_str(d) for d in (docs or ())],
                    }
                )
            out: dict = {"response": coerce_str(response)}
            if return_context_docs:
                out["context_docs"] = [coerce_str(d) for d in (docs or ())]
            return Json(out)

        return answered.select(
            result=ApplyExpression(
                pack, Json, answered.response, answered.return_context_docs,
                answered.docs,
            )
        )

    # -- summarize (reference: :491) --
    def summarize_query(self, summarize_queries: Table) -> Table:
        queries = summarize_queries.select(
            text_list=summarize_queries.text_list,
            model=ApplyExpression(
                lambda m: m or self.default_llm_name,
                dt.Optional(dt.STR),
                summarize_queries.model,
            ),
        )
        prompted = queries.select(
            prompt=self.summarize_template(queries.text_list),
            model=queries.model,
        )
        return prompted.select(
            result=self.llm(prompt_chat_single_qa(prompted.prompt), model=prompted.model)
        )

    # -- passthrough endpoints --
    def retrieve(self, queries: Table) -> Table:
        return self.indexer.retrieve_query(queries)

    def statistics(self, queries: Table) -> Table:
        return self.indexer.statistics_query(queries)

    def list_documents(self, queries: Table) -> Table:
        return self.indexer.inputs_query(queries)

    # -- TPU-native streamed answers (pathway_tpu.generation) -----------
    #
    # ``/v1/pw_ai_answer_stream`` serves end-to-end RAG answers with the
    # tokens generated ON the TPU by the paged-KV continuous-batching
    # decode subsystem: retrieval rides the serving scheduler as an
    # INTERACTIVE tick, generation rides the shared DecodeSession whose
    # ticks are GENERATE-class runtime work, and the answer streams back
    # over the existing webserver as chunked NDJSON lines.  The
    # external-UDF ``/v1/pw_ai_answer`` path is untouched — it remains
    # the fallback for non-TPU LLMs, and the breaker/degraded contract
    # is shared: a refused/failed generation answers retrieval-only with
    # ``"degraded": true`` instead of 5xx-ing.

    def _tpu_lm(self):
        """The TPU-native ``CausalLM`` when ``self.llm`` is a
        :class:`~pathway_tpu.xpacks.llm.llms.JaxPipelineChat` (duck-typed
        on ``_ensure_lm``), else ``None`` — streaming then answers 501
        and clients use the external-UDF endpoint."""
        ensure = getattr(self.llm, "_ensure_lm", None)
        if ensure is None:
            return None
        lm = ensure()
        return lm if hasattr(lm, "paged_session") else None

    def _stream_retrieve_plane(self):
        """A direct (non-dataflow) retrieval plane for the streaming
        handler, built once: DocumentStore exposes one; a
        VectorStoreServer-shaped indexer gets a fresh
        :class:`~pathway_tpu.xpacks.llm._scheduler.RetrievePlane` over
        its live index factory (same INTERACTIVE scheduling, breaker and
        BM25-degraded semantics as ``/v1/retrieve``)."""
        plane = getattr(self, "_stream_plane", None)
        if plane is not None or getattr(self, "_stream_plane_tried", False):
            return plane
        with getattr(self, "_stream_plane_lock", None) or threading.Lock():
            return self._stream_retrieve_plane_locked()

    def _stream_retrieve_plane_locked(self):
        plane = getattr(self, "_stream_plane", None)
        if plane is not None or getattr(self, "_stream_plane_tried", False):
            return plane
        ds_plane = getattr(self.indexer, "scheduler_retrieve_plane", None)
        try:
            if ds_plane is not None:
                plane = ds_plane()
            else:
                index_factory = getattr(self.indexer, "index_factory", None)
                graph = getattr(self.indexer, "_graph", None)
                embedder = getattr(self.indexer, "embedder", None) or getattr(
                    index_factory, "embedder", None
                )
                if index_factory is not None and graph is not None:
                    from ._scheduler import RetrievePlane

                    plane = RetrievePlane(
                        index_factory=index_factory,
                        embedder=embedder,
                        payload_columns=graph["chunked_docs"].column_names(),
                        label="qa_stream_retrieve",
                    )
        except Exception as exc:  # noqa: BLE001 — optional surface
            # a FAILED build stays retryable: latching the tried flag
            # here would turn one transient error (e.g. a lazy embedder
            # load hiccup) into a permanent 501 for the server's
            # lifetime — the tried-flag-on-success pattern from
            # RetrievePlane._cache_stack.  Logged once, not per request.
            if not getattr(self, "_stream_plane_err_logged", False):
                self._stream_plane_err_logged = True
                from ...internals.errors import register_error

                register_error(
                    f"streaming retrieve plane build failed (will retry "
                    f"on the next request): {type(exc).__name__}: {exc}",
                    kind="serving",
                    operator="pw_ai_answer_stream",
                )
            return None
        self._stream_plane = plane
        self._stream_plane_tried = True
        return plane

    def _stream_docs_k(self) -> int:
        """Context docs to retrieve for a streamed answer (the adaptive
        subclass needs its full escalation depth)."""
        return self.search_topk

    def _stream_rounds(
        self, lm, question: str, docs: list[str], *,
        max_new_tokens: int, temperature: float, seed: int,
        deadline_s: float | None, trace_link=None,
    ):
        """Yield ``("token", round, piece)`` events then one
        ``("final", round, answer)``.  Base: a single round over the
        paged continuous-batching session — per-TOKEN streaming, decode
        ticks shared with every concurrent request."""
        session = lm.paged_session()
        prompt = prompts.prompt_qa_geometric_rag(
            question, docs, information_not_found_response=_NO_INFO,
        )
        eos = lm.eos_id()
        handle = session.submit(
            lm.encode_prompt(prompt), max_new_tokens=max_new_tokens,
            temperature=temperature, seed=seed, eos_id=eos,
            deadline_s=deadline_s, trace_link=trace_link,
        )
        try:
            from ...generation.engine import iter_text_pieces

            parts: list[str] = []
            for piece in iter_text_pieces(handle, lm.decode_tokens, eos):
                parts.append(piece)
                yield ("token", 0, piece)
            yield ("final", 0, "".join(parts).strip())
        finally:
            # abandoned stream (client disconnect closes the generator
            # at a yield): stop decoding, free the blocks
            if not handle.done:
                session.cancel(handle)

    def answer_stream_handler(self):
        """The raw aiohttp handler behind ``/v1/pw_ai_answer_stream``:
        chunked ``application/x-ndjson`` — a ``context`` line (when
        requested), ``token`` lines as the device emits them, one
        terminal ``done`` line."""
        import asyncio
        import json as _json

        from ._utils import merge_filter_exprs

        _SENTINEL = object()

        async def handle(request):
            from aiohttp import web

            if request.method in ("POST", "PUT", "PATCH"):
                try:
                    payload = await request.json()
                except Exception:  # noqa: BLE001 — malformed body
                    return web.json_response(
                        {"detail": "request body is not valid JSON"},
                        status=400,
                    )
            else:
                payload = dict(request.query)
            prompt = coerce_str(payload.get("prompt", "")).strip()
            if not prompt:
                return web.json_response(
                    {"detail": "prompt is required"}, status=400
                )
            try:
                max_new = int(payload.get("max_new_tokens", 64))
                temperature = float(payload.get("temperature", 0.0))
                seed = int(payload.get("seed", 0))
                k = int(payload.get("k", self._stream_docs_k()))
                deadline_ms = payload.get("deadline_ms")
                deadline_s = (
                    None if deadline_ms is None
                    else float(deadline_ms) / 1000.0
                )
            except (TypeError, ValueError):
                return web.json_response(
                    {"detail": "invalid numeric parameter"}, status=400
                )
            raw_docs_flag = payload.get("return_context_docs", False)
            # GET requests deliver query-string values: "false"/"0" must
            # not truthy their way into the docs line
            return_docs = (
                raw_docs_flag.strip().lower() in ("1", "true", "yes")
                if isinstance(raw_docs_flag, str)
                else bool(raw_docs_flag)
            )
            # first-request lazy builds (CausalLM weight load, retrieve-
            # plane/embedder construction) can take tens of seconds —
            # off the event loop, or every concurrent /v1/retrieve and
            # /v1/pw_ai_answer response stalls behind them
            lm = await asyncio.to_thread(self._tpu_lm)
            if lm is None:
                return web.json_response(
                    {
                        "detail": "streaming requires a TPU-native LLM "
                        "(JaxPipelineChat); use /v1/pw_ai_answer",
                    },
                    status=501,
                )
            plane = await asyncio.to_thread(self._stream_retrieve_plane)
            if plane is None:
                return web.json_response(
                    {
                        "detail": "indexer exposes no direct retrieval "
                        "plane; use /v1/pw_ai_answer",
                    },
                    status=501,
                )
            flt = merge_filter_exprs(payload.get("filters"), None)
            from ._scheduler import DeadlineExceeded

            try:
                retrieved = await plane.scheduler.submit_async(
                    plane.group, (prompt, k, flt),
                    deadline_s=deadline_s, sheddable=True,
                    trace=request.get("pw_trace"),
                )
            except DeadlineExceeded as exc:
                return web.json_response(
                    {"detail": str(exc)},
                    status=503,
                    headers={"Retry-After": str(exc.retry_after_s)},
                )
            docs = [
                coerce_str(d.get("text", ""))
                for d in retrieved.get("results", ())
            ]
            # breaker contract shared with the UDF path: while open,
            # answer retrieval-only (degraded), never 5xx.  Checked
            # BEFORE the stream opens — one plain JSON line, which a
            # line-iterating stream client parses identically
            if not self.llm_breaker.allow():
                return web.json_response(
                    {
                        "event": "done",
                        "response": None,
                        "degraded": True,
                        "context_docs": docs,
                    }
                )
            import time as _time_mod

            from ...internals.flight_recorder import observe_stage, record_span
            from ...runtime import AdmissionRefused

            wall0 = _time_mod.time()
            t0 = _time_mod.monotonic()
            # thread the request's trace through to the decode launches:
            # the spans the device emits for this stream link back to it
            pw_trace = request.get("pw_trace")
            trace_link = (
                (pw_trace.trace_id, pw_trace.span_id)
                if pw_trace is not None and pw_trace.sampled
                else None
            )
            rounds_it = iter(
                self._stream_rounds(
                    lm, prompt, docs, max_new_tokens=max_new,
                    temperature=temperature, seed=seed, deadline_s=deadline_s,
                    trace_link=trace_link,
                )
            )

            def _gen_failed(exc: BaseException) -> dict:
                """Charge the LLM breaker (generation is actually sick)
                and build the degraded terminal line."""
                self.llm_breaker.record_failure(exc)
                from ...internals.errors import register_error

                register_error(
                    f"streamed generation failed, degraded to "
                    f"retrieval-only: {type(exc).__name__}: {exc}",
                    kind="serving",
                    operator="pw_ai_answer_stream",
                )
                dur_ms = (_time_mod.monotonic() - t0) * 1000.0
                record_span("llm", "llm", wall0, dur_ms, attrs={"ok": False})
                observe_stage("llm", dur_ms)
                return {
                    "event": "done",
                    "response": None,
                    "degraded": True,
                    "context_docs": docs,
                }

            def _contained_fault(exc: BaseException) -> dict:
                """Terminal error line for a generation-plane fault the
                engine contained (blast-radius isolation / pool
                recovery): a RETRYABLE server fault, not LLM sickness —
                never charged to the LLM breaker, and distinguishable
                from a network cut because the line still arrives."""
                from ...internals.errors import register_error

                register_error(
                    f"streamed generation hit a contained device fault: "
                    f"{type(exc).__name__}: {exc}",
                    kind="serving",
                    operator="pw_ai_answer_stream",
                )
                dur_ms = (_time_mod.monotonic() - t0) * 1000.0
                record_span("llm", "llm", wall0, dur_ms, attrs={"ok": False})
                observe_stage("llm", dur_ms)
                return {
                    "event": "error",
                    "kind": "error",
                    "retryable": True,
                    "detail": f"{type(exc).__name__}: {exc}",
                    "context_docs": docs,
                }

            # the FIRST pull runs decode admission: queue backpressure /
            # deadline sheds surface as real 503 + Retry-After (the
            # retrieval stage's contract) BEFORE headers go out, and are
            # never charged to the LLM breaker — shed ≠ sick
            try:
                first_ev = await asyncio.to_thread(next, rounds_it, _SENTINEL)
            except (AdmissionRefused, DeadlineExceeded) as exc:
                return web.json_response(
                    {"detail": str(exc)},
                    status=503,
                    headers={
                        "Retry-After": str(getattr(exc, "retry_after_s", 1.0))
                    },
                )
            except Exception as exc:  # noqa: BLE001 — degrade, don't 5xx
                from ...ops.device_faults import classify_device_error

                if classify_device_error(exc) is not None:
                    # contained device fault before headers: a retry hits
                    # a recovered engine — shed-shaped 503, no breaker
                    # charge
                    return web.json_response(
                        {"detail": str(exc), "retryable": True},
                        status=503,
                        headers={"Retry-After": "1.0"},
                    )
                return web.json_response(_gen_failed(exc))
            resp = web.StreamResponse(
                status=200,
                headers={
                    "Content-Type": "application/x-ndjson",
                    "Cache-Control": "no-cache",
                },
            )
            await resp.prepare(request)

            async def emit(obj: dict) -> None:
                await resp.write(
                    (_json.dumps(obj, ensure_ascii=False) + "\n").encode()
                )

            if return_docs or retrieved.get("degraded"):
                await emit(
                    {
                        "event": "context",
                        "context_docs": docs,
                        "retrieval_degraded": bool(retrieved.get("degraded")),
                    }
                )
            answer = None
            rounds = 0
            ev = first_ev
            while True:
                if ev is _SENTINEL:
                    break
                kind, rnd, text = ev
                rounds = max(rounds, rnd + 1)
                if kind == "token":
                    try:
                        await emit(
                            {"event": "token", "round": rnd, "text": text}
                        )
                    except Exception:  # noqa: BLE001 — client went away
                        # stop the generator (its finally cancels any
                        # live/retained sequence) and bail quietly — the
                        # generation path is healthy
                        await asyncio.to_thread(rounds_it.close)
                        return resp
                else:
                    answer = text
                # ONLY the generation pull is breaker-scoped — a client-
                # side write failure must not charge the LLM breaker
                # (the UDF path scopes record_failure the same way); a
                # mid-stream shed (e.g. an adaptive extend() the pool
                # cannot grow for) degrades without a breaker charge
                try:
                    ev = await asyncio.to_thread(next, rounds_it, _SENTINEL)
                except (AdmissionRefused, DeadlineExceeded):
                    await emit(
                        {
                            "event": "done",
                            "response": None,
                            "degraded": True,
                            "shed": True,
                            "context_docs": docs,
                        }
                    )
                    await resp.write_eof()
                    return resp
                except Exception as exc:  # noqa: BLE001 — degrade, don't 5xx
                    from ...ops.device_faults import classify_device_error

                    if classify_device_error(exc) is not None:
                        await emit(_contained_fault(exc))
                    else:
                        await emit(_gen_failed(exc))
                    await resp.write_eof()
                    return resp
            self.llm_breaker.record_success()
            dur_ms = (_time_mod.monotonic() - t0) * 1000.0
            record_span("llm", "llm", wall0, dur_ms, attrs={"ok": True})
            observe_stage("llm", dur_ms)
            await emit(
                {
                    "event": "done",
                    "response": answer,
                    "degraded": False,
                    "rounds": rounds,
                }
            )
            await resp.write_eof()
            return resp

        return handle

    # -- serving (reference: build_server/run_server) --
    def build_server(self, host: str, port: int, **rest_kwargs) -> None:
        from .servers import QASummaryRestServer

        self.server = QASummaryRestServer(host, port, self, **rest_kwargs)
        from ...io.http import EndpointDocumentation

        self.server.webserver.add_raw_route(
            "/v1/pw_ai_answer_stream",
            ("GET", "POST"),
            self.answer_stream_handler(),
            EndpointDocumentation(
                summary="Ask a question, stream the answer tokens "
                "(TPU-native paged decode)",
                tags=["pathway"],
            ),
        )

    def run_server(self, host: str = "0.0.0.0", port: int = 8000, **kwargs):
        if self.server is None:
            self.build_server(host=host, port=port)
        return self.server.run(**kwargs)


# ---------------------------------------------------------------------------
# adaptive RAG (reference: :97-162, :620)
# ---------------------------------------------------------------------------

_NO_INFO = "No information found."


def answer_with_geometric_rag_strategy(
    questions: Table,
    documents,  # ColumnReference to a list-of-docs column on `questions`
    llm_chat_model: BaseChat,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    strict_prompt: bool = False,
) -> Table:
    """Ask with 2, 4, 8, … context documents until the model answers
    (reference: question_answering.py:97).  Each escalation round runs only
    for the still-unanswered questions — chained filters, no fixpoint
    operator needed, exactly like the reference."""
    base = questions.select(question=questions.prompt, docs=documents)
    n_documents = n_starting_documents
    answered_tables: list[Table] = []
    remaining = base
    def make_prompt_udf(n: int):
        @udf
        def build_prompt(question: str, docs) -> str:
            doc_list = [coerce_str(d) for d in (docs or ())][:n]
            return prompts.prompt_qa_geometric_rag(
                question, doc_list,
                information_not_found_response=_NO_INFO,
                strict_prompt=strict_prompt,
            )

        return build_prompt

    for _ in range(max_iterations):
        build_prompt = make_prompt_udf(n_documents)
        asked = remaining.select(
            question=remaining.question,
            docs=remaining.docs,
            answer=llm_chat_model(
                prompt_chat_single_qa(build_prompt(remaining.question, remaining.docs))
            ),
        )
        found = asked.filter(
            ApplyExpression(
                lambda a: a is not None and coerce_str(a).strip() != _NO_INFO
                and coerce_str(a).strip() != "",
                dt.BOOL,
                asked.answer,
            )
        )
        answered_tables.append(found.select(result=found.answer))
        remaining = asked.filter(
            ApplyExpression(
                lambda a: a is None or coerce_str(a).strip() == _NO_INFO
                or coerce_str(a).strip() == "",
                dt.BOOL,
                asked.answer,
            )
        ).select(question=asked.question, docs=asked.docs)
        n_documents *= factor
    giving_up = remaining.select(
        result=ApplyExpression(lambda q: _NO_INFO, dt.STR, remaining.question)
    )
    result = answered_tables[0]
    return result.concat(*answered_tables[1:], giving_up)


def answer_with_geometric_rag_strategy_from_index(
    questions: Table,
    index,  # DataIndex
    documents_column: str,
    llm_chat_model: BaseChat,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    metadata_filter=None,
    strict_prompt: bool = False,
) -> Table:
    """reference: question_answering.py:162 — one index query fetches the
    max escalation depth, the strategy then slices locally."""
    max_docs = n_starting_documents * factor ** (max_iterations - 1)
    res = index.query_as_of_now(
        questions.prompt,
        number_of_matches=max_docs,
        metadata_filter=metadata_filter,
        collapse_rows=True,
    )
    with_docs = res.select(prompt=questions.prompt, docs=right[documents_column])
    return answer_with_geometric_rag_strategy(
        with_docs.select(prompt=with_docs.prompt),
        with_docs.docs,
        llm_chat_model,
        n_starting_documents=n_starting_documents,
        factor=factor,
        max_iterations=max_iterations,
        strict_prompt=strict_prompt,
    )


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """reference: question_answering.py:620"""

    def __init__(
        self,
        llm: BaseChat,
        indexer,
        *,
        default_llm_name: str | None = None,
        summarize_template=prompts.prompt_summarize,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
    ):
        super().__init__(
            llm, indexer,
            default_llm_name=default_llm_name,
            summarize_template=summarize_template,
        )
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        self.strict_prompt = strict_prompt

    def answer_query(self, pw_ai_queries: Table) -> Table:
        max_docs = self.n_starting_documents * self.factor ** (
            self.max_iterations - 1
        )
        retrieve_table = pw_ai_queries.select(
            query=pw_ai_queries.prompt,
            k=ApplyExpression(lambda p: max_docs, dt.INT, pw_ai_queries.prompt),
            metadata_filter=pw_ai_queries.filters,
            filepath_globpattern=ApplyExpression(
                lambda p: None, dt.Optional(dt.STR), pw_ai_queries.prompt
            ),
        )
        docs_result = self.indexer.retrieve_query(retrieve_table)
        with_docs = pw_ai_queries.with_universe_of(docs_result).select(
            prompt=pw_ai_queries.prompt,
            docs=ApplyExpression(
                lambda r: tuple(
                    d.get("text") if isinstance(d, dict) else d
                    for d in (r.value if isinstance(r, Json) else r or ())
                ),
                dt.List(dt.STR),
                docs_result.result,
            ),
        )
        answers = answer_with_geometric_rag_strategy(
            with_docs,
            with_docs.docs,
            self.llm,
            n_starting_documents=self.n_starting_documents,
            factor=self.factor,
            max_iterations=self.max_iterations,
            strict_prompt=self.strict_prompt,
        )
        # restore the query universe for the response writer
        packed = answers.select(
            result=ApplyExpression(
                lambda a: Json({"response": coerce_str(a)}), Json, answers.result
            )
        )
        return pw_ai_queries.with_universe_of(packed).select(result=packed.result)

    def _stream_docs_k(self) -> int:
        """Full escalation depth — the non-streaming adaptive path
        retrieves the same amount (answer_query's max_docs)."""
        return self.n_starting_documents * self.factor ** (
            self.max_iterations - 1
        )

    def _stream_rounds(
        self, lm, question: str, docs: list[str], *,
        max_new_tokens: int, temperature: float, seed: int,
        deadline_s: float | None, trace_link=None,
    ):
        """Geometric escalation over LIVE KV blocks: round 1 prefills
        the n_starting-docs prompt with ``retain=True``; an unanswered
        round does NOT re-queue from scratch — :meth:`DecodeSession.extend`
        appends only the NEW sources + re-ask to the retained sequence's
        paged blocks, so escalation cost is the delta, not the whole
        prompt again (pinned: prefill token counter advances once)."""
        session = lm.paged_session()
        eos = lm.eos_id()
        n = self.n_starting_documents
        handle = None
        consumed = 0
        try:
            for rnd in range(self.max_iterations):
                if handle is None:
                    prompt = prompts.prompt_qa_geometric_rag(
                        question, docs[:n],
                        information_not_found_response=_NO_INFO,
                        strict_prompt=self.strict_prompt,
                    )
                    handle = session.submit(
                        lm.encode_prompt(prompt),
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, seed=seed, eos_id=eos,
                        deadline_s=deadline_s, retain=True,
                        trace_link=trace_link,
                    )
                else:
                    extra = docs[consumed:n]
                    cont = (
                        "\n"
                        + "\n".join(
                            f"Source {consumed + i + 1}: {d}"
                            for i, d in enumerate(extra)
                        )
                        + f"\nQuestion: {question}\nAnswer:"
                    )
                    handle = session.extend(
                        handle, lm.encode_prompt(cont),
                        max_new_tokens=max_new_tokens,
                    )
                consumed = min(n, len(docs))
                from ...generation.engine import iter_text_pieces

                parts: list[str] = []
                for piece in iter_text_pieces(handle, lm.decode_tokens, eos):
                    parts.append(piece)
                    yield ("token", rnd, piece)
                answer = "".join(parts).strip()
                if answer and answer != _NO_INFO:
                    yield ("final", rnd, answer)
                    return
                if consumed >= len(docs):
                    # no new sources left to escalate with
                    break
                n *= self.factor
            yield ("final", rnd, _NO_INFO)
        finally:
            # cancel() covers every state: retained (normal end), still
            # live (client abandoned the stream mid-round), queued
            if handle is not None:
                session.cancel(handle)


class DeckRetriever(BaseRAGQuestionAnswerer):
    """Slide-deck retrieval app (reference: question_answering.py:736)."""

    excluded_response_metadata = ["b64_image"]

    def answer_query(self, pw_ai_queries: Table) -> Table:
        retrieve_table = pw_ai_queries.select(
            query=pw_ai_queries.prompt,
            k=ApplyExpression(lambda p: self.search_topk, dt.INT, pw_ai_queries.prompt),
            metadata_filter=pw_ai_queries.filters,
            filepath_globpattern=ApplyExpression(
                lambda p: None, dt.Optional(dt.STR), pw_ai_queries.prompt
            ),
        )
        docs = self.indexer.retrieve_query(retrieve_table)

        def strip_meta(r) -> Json:
            out = []
            for d in r.value if isinstance(r, Json) else (r or ()):
                if isinstance(d, dict):
                    d = dict(d)
                    meta = d.get("metadata") or {}
                    d["metadata"] = {
                        k: v for k, v in meta.items()
                        if k not in self.excluded_response_metadata
                    }
                out.append(d)
            return Json(out)

        return docs.select(
            result=ApplyExpression(strip_meta, Json, docs.result)
        )


# ---------------------------------------------------------------------------
# client (reference: question_answering.py:854)
# ---------------------------------------------------------------------------


class RAGClient(RestClientBase):
    """HTTP client for QARestServer/QASummaryRestServer."""

    def __init__(self, *args, timeout: float = 90.0, **kwargs):
        super().__init__(*args, timeout=timeout, **kwargs)

    def retrieve(
        self,
        query: str,
        k: int = 3,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ):
        return self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )

    def statistics(self):
        return self._post("/v1/statistics", {})

    def pw_list_documents(self, filters: str | None = None, keys: list | None = None):
        return self._post("/v1/pw_list_documents", {"metadata_filter": filters})

    def pw_ai_answer(
        self,
        prompt: str,
        filters: str | None = None,
        model: str | None = None,
        return_context_docs: bool = False,
        response_type: str = AIResponseType.SHORT,
    ):
        payload: dict = {
            "prompt": prompt,
            "return_context_docs": return_context_docs,
            "response_type": response_type,
        }
        if filters is not None:
            payload["filters"] = filters
        if model is not None:
            payload["model"] = model
        return self._post("/v1/pw_ai_answer", payload)

    answer = pw_ai_answer

    def pw_ai_answer_stream(
        self,
        prompt: str,
        filters: str | None = None,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        seed: int = 0,
        return_context_docs: bool = False,
        deadline_ms: float | None = None,
    ):
        """Stream ``/v1/pw_ai_answer_stream`` NDJSON events as dicts
        (``context`` / ``token`` / ``done`` / ``error``) as the server
        emits them.

        A terminal ``{"kind": "error", "retryable": true}`` line means
        the server hit a *contained* generation-plane fault mid-stream
        (blast-radius isolation or KV-pool recovery): the stream ended
        early but the server is healthy and a retried request will hit a
        recovered engine.  A connection that dies with NO terminal
        ``done``/``error`` line is a network cut — the two are
        deliberately distinguishable."""
        import json as _json
        import urllib.request

        payload: dict = {
            "prompt": prompt,
            "max_new_tokens": max_new_tokens,
            "temperature": temperature,
            "seed": seed,
            "return_context_docs": return_context_docs,
        }
        if filters is not None:
            payload["filters"] = filters
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        req = urllib.request.Request(
            f"{self.url}/v1/pw_ai_answer_stream",
            data=_json.dumps(payload).encode(),
            headers={
                "Content-Type": "application/json",
                # client-minted W3C context, same contract as _post:
                # the server adopts it and the stream's retrieval +
                # decode spans land under ONE client-known trace id
                "traceparent": self._new_traceparent(),
                **self.additional_headers,
            },
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            self.last_trace_id = resp.headers.get("x-pathway-trace-id")
            for line in resp:
                line = line.strip()
                if line:
                    yield _json.loads(line)

    def pw_ai_summary(self, text_list: list[str], model: str | None = None):
        payload: dict = {"text_list": text_list}
        if model is not None:
            payload["model"] = model
        return self._post("/v1/pw_ai_summary", payload)

    summarize = pw_ai_summary
