"""Serving circuit breakers.

A live RAG service must keep answering when a stage starts failing — an
embedder OOMs, an upstream LLM times out (VectorLiteRAG, arXiv
2504.08930: latency-aware fallback when one pipeline stage becomes the
bottleneck; EdgeRAG, arXiv 2412.21023: degrade gracefully, don't fail
closed).  The breaker is the switch that turns repeated stage failures
into a *fast, deliberate* fallback instead of per-request timeouts:

* CLOSED — normal operation; consecutive failures are counted;
* OPEN — tripped after ``failure_threshold`` consecutive failures: calls
  are refused instantly (callers take their degraded path) for
  ``cooldown_s``;
* HALF_OPEN — after the cooldown one probe call is admitted; success
  closes the breaker, failure re-opens it for another cooldown.

Breakers register with the health registry (``breaker:<name>``
components, OPEN/HALF_OPEN = degraded-but-ready) and with the
OpenMetrics plane (``pathway_breaker_*`` series via
``register_metrics_provider``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

__all__ = ["CircuitBreaker", "BreakerOpen"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class BreakerOpen(RuntimeError):
    """Raised by :meth:`CircuitBreaker.call` when the breaker refuses."""


class CircuitBreaker:
    """Thread-safe consecutive-failure circuit breaker (module docstring)."""

    def __init__(
        self,
        name: str,
        *,
        failure_threshold: int | None = None,
        cooldown_s: float | None = None,
        probe_timeout_s: float = 60.0,
    ):
        self.name = name
        self.failure_threshold = (
            failure_threshold
            if failure_threshold is not None
            else int(os.environ.get("PATHWAY_BREAKER_FAILURES", "3"))
        )
        self.cooldown_s = (
            cooldown_s
            if cooldown_s is not None
            else float(os.environ.get("PATHWAY_BREAKER_COOLDOWN_S", "5.0"))
        )
        #: a HALF_OPEN probe whose caller never reports back (cancelled
        #: task, BaseException) releases its slot after this long — else
        #: the breaker would refuse forever
        self.probe_timeout_s = max(probe_timeout_s, self.cooldown_s)
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._probe_granted_at = 0.0
        self._counters = {
            "trips_total": 0,
            "refused_total": 0,
            "failures_total": 0,
            "successes_total": 0,
            "last_error": "",
        }
        from ...internals.health import get_health
        from ...internals.monitoring import register_metrics_provider

        self._health = get_health()
        self._publish_health()
        register_metrics_provider(f"breaker:{name}", self)

    # -- state machine ---------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:
        # caller holds the lock
        if self._state == OPEN and (
            time.monotonic() - self._opened_at >= self.cooldown_s
        ):
            self._state = HALF_OPEN
            self._probe_in_flight = False
        return self._state

    def allow(self) -> bool:
        """True when a call may proceed.  In HALF_OPEN exactly one caller
        gets the probe slot until its outcome is recorded (or the probe
        times out — a vanished prober must not wedge the breaker)."""
        with self._lock:
            state = self._effective_state()
            if state == CLOSED:
                return True
            if state == HALF_OPEN:
                if self._probe_in_flight and (
                    time.monotonic() - self._probe_granted_at
                    > self.probe_timeout_s
                ):
                    self._probe_in_flight = False
                if not self._probe_in_flight:
                    self._probe_in_flight = True
                    self._probe_granted_at = time.monotonic()
                    return True
            self._counters["refused_total"] += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            prev = self._state
            self._counters["successes_total"] += 1
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state != CLOSED:
                self._state = CLOSED
            new = self._state
        self._note_transition(prev, new)
        self._publish_health()

    def record_failure(self, exc: BaseException | None = None) -> None:
        with self._lock:
            prev = self._state
            self._counters["failures_total"] += 1
            if exc is not None:
                self._counters["last_error"] = f"{type(exc).__name__}: {exc}"
            self._probe_in_flight = False
            if self._state == HALF_OPEN:
                # failed probe: straight back to OPEN for another cooldown
                self._state = OPEN
                self._opened_at = time.monotonic()
                self._counters["trips_total"] += 1
            else:
                self._consecutive_failures += 1
                if (
                    self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold
                ):
                    self._state = OPEN
                    self._opened_at = time.monotonic()
                    self._counters["trips_total"] += 1
            new = self._state
        self._note_transition(prev, new)
        self._publish_health()

    def _note_transition(self, prev: str, new: str) -> None:
        """Breaker state changes are flight-recorder events: a degraded
        window in a trace dump lines up with the trip that caused it."""
        if prev == new:
            return
        from ...internals.flight_recorder import record_span

        record_span(
            f"breaker:{self.name}:{prev}->{new}",
            "breaker",
            time.time(),
            0.0,
            attrs={"breaker": self.name, "from": prev, "to": new},
        )

    def call(self, fn, *args, **kwargs):
        """Run ``fn`` through the breaker: refused → :class:`BreakerOpen`;
        outcome recorded either way."""
        if not self.allow():
            raise BreakerOpen(f"circuit breaker {self.name!r} is open")
        try:
            result = fn(*args, **kwargs)
        except Exception as exc:
            self.record_failure(exc)
            raise
        self.record_success()
        return result

    # -- observability ---------------------------------------------------
    def _publish_health(self) -> None:
        state = self.state
        self._health.set_component(
            f"breaker:{self.name}",
            state,
            ready=True,
            degraded=state != CLOSED,
            critical=False,
            detail=self._counters["last_error"] if state != CLOSED else "",
            scope="process",
        )

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "state": self._effective_state(),
                "consecutive_failures": self._consecutive_failures,
                **self._counters,
            }

    def openmetrics_lines(self) -> list[str]:
        from ...internals.metrics_names import escape_label_value

        s = self.stats()
        lbl = f'breaker="{escape_label_value(self.name)}"'
        state_code = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}[s["state"]]
        lines = [
            "# TYPE pathway_breaker_state gauge",
            f"pathway_breaker_state{{{lbl}}} {state_code}",
        ]
        for metric in (
            "trips_total", "refused_total", "failures_total",
            "successes_total",
        ):
            lines.append(f"# TYPE pathway_breaker_{metric} counter")
            lines.append(f"pathway_breaker_{metric}{{{lbl}}} {s[metric]}")
        return lines
