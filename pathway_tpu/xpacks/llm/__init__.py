"""``pw.xpacks.llm`` — the live LLM/RAG toolkit, TPU-native.

reference: python/pathway/xpacks/llm/__init__.py.  The component families
(embedders / llms / rerankers / parsers / splitters / prompts) are
``pw.UDF`` subclasses exactly like the reference; the local-model ones
(SentenceTransformerEmbedder, CrossEncoderReranker) run as jit-compiled
JAX modules on the TPU instead of torch-on-CPU/GPU inside the UDF.
"""

from typing import Callable, Iterable, TypeAlias, Union

from ...internals.udfs import UDF as _UDF

from . import (
    embedders,
    llms,
    mocks,
    parsers,
    prompts,
    rerankers,
    splitters,
)

# document-transformer typing surface (reference: xpacks/llm/_typing.py)
Doc: TypeAlias = dict[str, str | dict]
DocTransformerCallable: TypeAlias = Union[
    Callable[[Iterable[Doc]], Iterable[Doc]],
    Callable[[Iterable[Doc], float], Iterable[Doc]],
]
DocTransformer: TypeAlias = Union[_UDF, DocTransformerCallable]

__all__ = [
    "embedders",
    "llms",
    "mocks",
    "parsers",
    "prompts",
    "rerankers",
    "splitters",
    "vector_store",
    "document_store",
    "question_answering",
    "Doc",
    "DocTransformer",
    "DocTransformerCallable",
    "servers",
    "IngestPipeline",
]


def __getattr__(name: str):
    if name == "IngestPipeline":
        from ._ingest import IngestPipeline

        globals()[name] = IngestPipeline
        return IngestPipeline
    # heavier modules (servers pull in aiohttp) load lazily
    if name in ("vector_store", "document_store", "question_answering", "servers"):
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
