"""VectorStoreServer — live document indexing + retrieval serving.

reference: python/pathway/xpacks/llm/vector_store.py —
``VectorStoreServer``:39 (pipeline ``_build_graph``:227: sources → parse →
flatten → post-process → split → flatten → index:289; stats reduce :303;
REST endpoints ``/v1/retrieve|statistics|inputs`` :523-556;
``run_server``:558), ``VectorStoreClient``:651, LangChain :92 /
LlamaIndex :136 adapters.

TPU shape: chunks stream through the jit-compiled embedder (one padded
device batch per engine micro-batch) into the HBM-resident KNN index
(ops/knn.py); queries ride the same as-of-now external-index operator the
reference uses (updates-before-queries per timestamp, lowering.py).
"""

from __future__ import annotations

from typing import Any, Callable

from ...internals import dtype as dt
from ...internals import reducers
from ...internals.expression import ApplyExpression
from ...internals.schema import Schema, column_definition
from ...internals.table import Table
from ...internals.udfs import udf
from ...internals.value import Json
from ...stdlib.indexing.data_index import DataIndex
from ...stdlib.indexing.retrievers import UsearchKnnFactory
from ._utils import RestClientBase, coerce_str, run_with_cache
from .parsers import Utf8Parser
from .splitters import null_splitter

__all__ = ["VectorStoreServer", "VectorStoreClient", "SlidesVectorStoreServer"]


# ---------------------------------------------------------------------------
# query schemas (reference: vector_store.py RetrieveQuerySchema et al.)
# ---------------------------------------------------------------------------


class RetrieveQuerySchema(Schema):
    query: str
    k: int = column_definition(default_value=3)
    metadata_filter: str | None = column_definition(default_value=None)
    filepath_globpattern: str | None = column_definition(default_value=None)


class StatisticsQuerySchema(Schema):
    req: str | None = column_definition(default_value=None)


class InputsQuerySchema(Schema):
    metadata_filter: str | None = column_definition(default_value=None)
    filepath_globpattern: str | None = column_definition(default_value=None)


class QueryResultSchema(Schema):
    result: Json


@udf(deterministic=True)
def _merge_filters(metadata_filter: str | None, filepath_globpattern: str | None) -> str | None:
    """Combine the two request filters into one expression
    (reference: vector_store.py:358 ``merge_filters``).  Deterministic:
    a pure string merge — marking it so keeps its select un-memoized,
    which OPERATOR_PERSISTING's coverage check requires (a memoized map
    cannot restart empty over restored downstream state)."""
    from ._utils import merge_filter_exprs

    return merge_filter_exprs(metadata_filter, filepath_globpattern)


from ._pipeline import build_document_pipeline, component_expr as _component_expr


def _wire_index_maintenance(retrieve_query_fn, query_schema) -> None:
    """Keep the external-index operator in the graph when the scheduler
    plane answers queries: an empty static query stream through the same
    ``retrieve_query`` pipeline makes the engine build and continuously
    maintain the index (docs embed/upsert per micro-batch) while REST
    retrieval reads it through the admission queue instead."""
    from ...debug import table_from_rows
    from ...io._subscribe import subscribe

    queries = table_from_rows(query_schema, [])
    result = retrieve_query_fn(queries)
    subscribe(result, on_change=lambda *a, **k: None, name="index-maintain")


class VectorStoreServer:
    """reference: vector_store.py:39"""

    def __init__(
        self,
        *docs: Table,
        embedder: Callable | None = None,
        parser: Callable | None = None,
        splitter: Callable | None = None,
        doc_post_processors: list[Callable] | None = None,
        index_factory: Any = None,
        mesh: Any = None,
    ):
        self.docs = list(docs)
        self.embedder = embedder
        self.parser = parser if parser is not None else Utf8Parser()
        self.splitter = splitter if splitter is not None else null_splitter
        self.doc_post_processors = [p for p in (doc_post_processors or []) if p is not None]
        if mesh is None:
            # PATHWAY_SERVING_MESH: env-default multi-chip serving — the
            # live index shards over the mesh's data axis and every fused
            # serving tick merges per-shard top-k over ICI
            from ...parallel.mesh import serving_mesh

            mesh = serving_mesh()
        # a model-backed embedder whose encoder is not built yet inherits
        # the serving mesh: query/ingest encodes then run data-parallel
        # over the same device set the index shards on
        from ._utils import seed_embedder_mesh

        seed_embedder_mesh(embedder, mesh)
        if index_factory is None:
            if embedder is None:
                raise ValueError("provide embedder= or index_factory=")
            index_factory = UsearchKnnFactory(embedder=embedder, mesh=mesh)
        elif mesh is not None and getattr(index_factory, "mesh", "-") is None:
            # device-mesh knob (SURVEY §2.7): shard the KNN matrix over the
            # mesh's data axis instead of replicating per worker like the
            # reference (external_index.rs:95-98 broadcast replica).  Only
            # factories exposing an unset ``mesh`` field participate; the
            # caller's factory object is left untouched.
            import dataclasses as _dc

            index_factory = _dc.replace(index_factory, mesh=mesh)
        self.mesh = mesh
        self.index_factory = index_factory
        self._graph = self._build_graph()

    # -- classmethod adapters (reference: vector_store.py:92,136) --
    @classmethod
    def from_langchain_components(
        cls, *docs, embedder, parser=None, splitter=None, **kwargs
    ) -> "VectorStoreServer":
        """Wrap LangChain embeddings + text splitter."""

        @udf
        async def generic_embedder(x: str):
            import numpy as np

            res = await embedder.aembed_query(coerce_str(x))
            return np.asarray(res)

        generic_splitter = None
        if splitter is not None:
            generic_splitter = lambda x: [  # noqa: E731
                (c, {}) for c in splitter.split_text(coerce_str(x))
            ]
        return cls(
            *docs, embedder=generic_embedder, parser=parser,
            splitter=generic_splitter, **kwargs,
        )

    @classmethod
    def from_llamaindex_components(
        cls, *docs, transformations: list, parser=None, **kwargs
    ) -> "VectorStoreServer":
        """Wrap a LlamaIndex embedding + node-parser transformation chain."""
        try:
            from llama_index.core.base.embeddings.base import BaseEmbedding
            from llama_index.core.node_parser.interface import TextSplitter
        except ImportError as exc:  # pragma: no cover - optional dependency
            raise ImportError("llama-index-core is required") from exc

        embedders_ = [t for t in transformations if isinstance(t, BaseEmbedding)]
        if len(embedders_) != 1:
            raise ValueError("transformations must include exactly one embedder")
        embedder = embedders_[0]

        @udf
        async def generic_embedder(x: str):
            import numpy as np

            return np.asarray(await embedder.aget_text_embedding(coerce_str(x)))

        splitters_ = [t for t in transformations if isinstance(t, TextSplitter)]
        generic_splitter = None
        if splitters_:
            sp = splitters_[0]
            generic_splitter = lambda x: [(c, {}) for c in sp.split_text(coerce_str(x))]  # noqa: E731
        return cls(
            *docs, embedder=generic_embedder, parser=parser,
            splitter=generic_splitter, **kwargs,
        )

    # -- pipeline (reference: vector_store.py:227 _build_graph) --
    def _build_graph(self) -> dict:
        graph = build_document_pipeline(
            self.docs, self.parser, self.splitter, self.doc_post_processors
        )
        graph["index"] = DataIndex(
            graph["chunked_docs"],
            self.index_factory,
            data_column=graph["chunked_docs"].text,
            metadata_column=graph["chunked_docs"].metadata,
            embedder=self.embedder,
        )
        return graph

    # -- embedding dimension probe (reference: vector_store.py embedder probe) --
    @property
    def embedding_dimension(self) -> int:
        factory = self.index_factory
        return factory._resolve_dim(getattr(factory, "dimensions", None), self.embedder)

    # -- query pipelines --
    def retrieve_query(self, retrieval_queries: Table) -> Table:
        """reference: vector_store.py:439"""
        queries = retrieval_queries.select(
            query=retrieval_queries.query,
            k=retrieval_queries.k,
            metadata_filter=_merge_filters(
                retrieval_queries.metadata_filter,
                retrieval_queries.filepath_globpattern,
            ),
        )
        index: DataIndex = self._graph["index"]
        res = index.query_as_of_now(
            queries.query,
            number_of_matches=queries.k,
            metadata_filter=queries.metadata_filter,
            collapse_rows=True,
        )

        def pack(texts, metas, scores) -> Json:
            out = []
            for t, m, s in zip(texts or (), metas or (), scores or ()):
                out.append(
                    {
                        "text": coerce_str(t),
                        "metadata": m.value if isinstance(m, Json) else m,
                        "dist": -float(s),
                    }
                )
            return Json(out)

        from ...internals.thisclass import right

        return res.select(
            result=ApplyExpression(
                pack,
                Json,
                right.text,
                right.metadata,
                right["_pw_index_reply_score"],
            )
        )

    def statistics_query(self, info_queries: Table) -> Table:
        """reference: vector_store.py statistics endpoint"""
        stats = self._graph["stats"]

        def pack_stats(count, last_modified, last_indexed) -> Json:
            return Json(
                {
                    "file_count": int(count or 0),
                    "last_modified": last_modified,
                    "last_indexed": last_indexed,
                }
            )

        joined = info_queries.join_left(stats, id=info_queries.id).select(
            result=ApplyExpression(
                pack_stats, Json, stats.count, stats.last_modified, stats.last_indexed
            )
        )
        return joined

    def inputs_query(self, input_queries: Table) -> Table:
        """reference: vector_store.py inputs endpoint"""
        docs = self._graph["parsed_docs"]
        all_meta = docs.reduce(
            metadatas=reducers.tuple(docs.metadata),
        )

        @udf
        def format_inputs(metadatas, metadata_filter: str | None) -> Json:
            from ...utils.jmespath_lite import compile_filter

            metas = [m.value if isinstance(m, Json) else m for m in (metadatas or ())]
            if metadata_filter:
                flt = compile_filter(metadata_filter)
                metas = [m for m in metas if flt(m)]
            return Json(metas)

        queries = input_queries.select(
            metadata_filter=_merge_filters(
                input_queries.metadata_filter, input_queries.filepath_globpattern
            )
        )
        return queries.join_left(all_meta, id=queries.id).select(
            result=format_inputs(all_meta.metadatas, queries.metadata_filter)
        )

    # -- serving (reference: vector_store.py:523-582) --
    def build_server(
        self,
        host: str,
        port: int,
        *,
        with_scheduler: bool | None = None,
        deadline_ms: float | None = None,
        aux_endpoints: bool = True,
        **rest_kwargs,
    ) -> None:
        """Register the REST routes.

        ``with_scheduler`` (default: the global setting, on unless
        ``PATHWAY_SERVING_SCHEDULER=0``) serves ``/v1/retrieve`` off the
        continuous cross-request scheduler — concurrent queries coalesce
        into one fused embed→search device tick instead of riding engine
        micro-batch cadence — with ``deadline_ms``-based shedding
        (503 + Retry-After).  Statistics/inputs stay engine-routed.

        Under the unified device-tick runtime (``PATHWAY_RUNTIME=1``,
        the default) those ticks execute as ``INTERACTIVE``-class work
        on the process-wide QoS executor: they preempt bulk-ingest
        chunks at tick granularity, so serving p99 survives ingest
        bursts (see README "Operations: unified runtime & QoS classes";
        per-class state rides ``/v1/health`` and ``/status``).

        ``aux_endpoints=False`` registers only ``/v1/retrieve`` (plus the
        always-on ``/v1/health`` and ``/v1/debug/traces``): the
        statistics/inputs pipelines join REST queries against engine
        state, and those joins are not yet covered by the
        OPERATOR_PERSISTING recovery plane — a durable serving deployment
        (see README "Operations: recovery & durability") runs
        retrieve-only.

        Every route is traced: responses carry ``x-pathway-trace-id``
        (a caller-sent W3C ``traceparent`` is honored) and the scheduler
        path records a per-stage breakdown (queue wait / embed / search /
        serialize) retrievable from ``GET /v1/debug/traces`` on the same
        server — see README "Operations: observability".
        """
        from ...io.http import PathwayWebserver, rest_connector

        webserver = PathwayWebserver(host=host, port=port)
        self._webserver = webserver

        # fleet membership control surface (/v1/fleet/ingest|drain|
        # watermark): wired only when this process activated a member —
        # a standalone server never registers the routes
        import sys as _sys

        _member_mod = _sys.modules.get("pathway_tpu.fleet.member")
        if _member_mod is not None:
            _member = _member_mod.get_member()
            if _member is not None:
                _member.wire_routes(webserver)

        embedder = self.embedder or getattr(self.index_factory, "embedder", None)
        if with_scheduler is None:
            from ._scheduler import scheduler_enabled

            with_scheduler = scheduler_enabled() and embedder is not None
        elif with_scheduler and embedder is None:
            # fail at build time, not as a 500 on every query
            raise ValueError(
                "with_scheduler=True needs an embedder (the fused retrieve "
                "plane embeds queries itself); pass embedder= or use an "
                "index factory that carries one"
            )
        if with_scheduler:
            from ._scheduler import RetrievePlane

            self._retrieve_plane = RetrievePlane(
                index_factory=self.index_factory,
                embedder=embedder,
                payload_columns=self._graph["chunked_docs"].column_names(),
                deadline_ms=deadline_ms,
            )
            webserver.add_raw_route(
                "/v1/retrieve", ("GET", "POST"), self._retrieve_plane.aiohttp_handler()
            )
            _wire_index_maintenance(self.retrieve_query, RetrieveQuerySchema)
        else:
            retrieval_queries, retrieval_writer = rest_connector(
                webserver=webserver,
                route="/v1/retrieve",
                schema=RetrieveQuerySchema,
                methods=("GET", "POST"),
                delete_completed_queries=True,
            )
            retrieval_writer(self.retrieve_query(retrieval_queries))

        if not aux_endpoints:
            # no rest_connector subject will start the listener (the
            # scheduler plane serves /v1/retrieve directly) — bring it up
            # now so /v1/health is observable through warm restore, with
            # queries answering degraded until the index is ready
            webserver._ensure_started()
            return

        stats_queries, stats_writer = rest_connector(
            webserver=webserver,
            route="/v1/statistics",
            schema=StatisticsQuerySchema,
            methods=("GET", "POST"),
            delete_completed_queries=True,
        )
        stats_writer(self.statistics_query(stats_queries))

        input_queries, inputs_writer = rest_connector(
            webserver=webserver,
            route="/v1/inputs",
            schema=InputsQuerySchema,
            methods=("GET", "POST"),
            delete_completed_queries=True,
        )
        inputs_writer(self.inputs_query(input_queries))

    def run_server(
        self,
        host: str = "0.0.0.0",
        port: int = 8000,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = True,
        with_scheduler: bool | None = None,
        deadline_ms: float | None = None,
        aux_endpoints: bool = True,
        persistence_config: Any = None,
    ):
        """Start serving; ``threaded=True`` runs the engine loop on a daemon
        thread and returns it (reference: vector_store.py:558-582).
        ``with_scheduler``/``deadline_ms``/``aux_endpoints`` — see
        :meth:`build_server`.  ``persistence_config`` (a
        ``pw.persistence.Config``) makes the server durable: with
        ``PersistenceMode.OPERATOR_PERSISTING`` the live HBM index
        checkpoints already-computed vectors per commit and warm-restarts
        from them (zero re-embeddings) behind the ``/v1/health`` gate."""
        self.build_server(
            host=host, port=port,
            with_scheduler=with_scheduler, deadline_ms=deadline_ms,
            aux_endpoints=aux_endpoints,
        )
        return run_with_cache(
            threaded=threaded,
            with_cache=with_cache,
            cache_backend=cache_backend,
            terminate_on_error=terminate_on_error,
            persistence_config=persistence_config,
        )


class SlidesVectorStoreServer(VectorStoreServer):
    """Parity alias for the slide-deck flavor (reference:
    vector_store.py SlidesVectorStoreServer)."""


class VectorStoreClient(RestClientBase):
    """HTTP client for :class:`VectorStoreServer`
    (reference: vector_store.py:651).

    ``retry_on_unavailable=True`` honors the scheduler's
    503 + ``Retry-After`` shedding with one bounded retry (off by
    default — callers owning their own backoff keep full control).
    ``last_trace_id`` holds the server's trace id for the most recent
    call — feed it to ``/v1/debug/traces?trace_id=`` for the per-stage
    latency breakdown of that exact request."""

    def __init__(self, *args, timeout: float = 15.0, **kwargs):
        super().__init__(*args, timeout=timeout, **kwargs)
        #: True when the last /v1/retrieve answer came from the degraded
        #: (lexical fallback) path — see RetrievePlane's breaker
        self.last_degraded = False

    def query(
        self,
        query: str,
        k: int = 3,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ) -> list[dict]:
        payload = {"query": query, "k": k}
        if metadata_filter is not None:
            payload["metadata_filter"] = metadata_filter
        if filepath_globpattern is not None:
            payload["filepath_globpattern"] = filepath_globpattern
        res = self._post("/v1/retrieve", payload)
        if isinstance(res, dict) and "results" in res:
            self.last_degraded = bool(res.get("degraded"))
            return res["results"]
        self.last_degraded = False
        return res

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        return self._post("/v1/statistics", {})

    def get_input_files(
        self,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ) -> list:
        return self._post(
            "/v1/inputs",
            {
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )
