"""Splitter UDFs — document chunking.

reference: python/pathway/xpacks/llm/splitters.py — ``null_splitter``:12,
``TokenCountSplitter``:34 (tiktoken-based, min/max token window with
punctuation-aware cut points).

The chunker works over *character spans* of the original text: tiktoken
provides them via ``decode_with_offsets`` when importable; otherwise a
regex word tokenizer supplies the spans.  Either way the emitted chunks are
exact substrings of the input (the reference re-decodes token slices, which
can mangle e.g. split multi-byte sequences).
"""

from __future__ import annotations

import bisect
import re

from ...internals.udfs import UDF
from ._utils import coerce_str

__all__ = ["NullSplitter", "null_splitter", "TokenCountSplitter"]

_WORD_RE = re.compile(r"\w+|[^\w\s]", re.UNICODE)
_CUT_RE = re.compile(r"[.?!\n]")


def null_splitter(txt: str) -> list[tuple[str, dict]]:
    """One chunk per document, no metadata (reference: splitters.py:12)."""
    return [(coerce_str(txt), {})]


class NullSplitter(UDF):
    """UDF form of :func:`null_splitter`."""

    def __init__(self):
        super().__init__(deterministic=True)

    def __wrapped__(self, txt: str, **kwargs) -> list[tuple[str, dict]]:
        return null_splitter(txt)


def _token_spans(text: str, encoding_name: str) -> list[tuple[int, int]]:
    """(start, end) character span per token."""
    try:
        import tiktoken

        enc = tiktoken.get_encoding(encoding_name)
        tokens = enc.encode(text)
        _, offsets = enc.decode_with_offsets(tokens)
        spans = []
        for i, start in enumerate(offsets):
            end = offsets[i + 1] if i + 1 < len(offsets) else len(text)
            spans.append((start, end))
        return spans
    except Exception:
        return [(m.start(), m.end()) for m in _WORD_RE.finditer(text)]


class TokenCountSplitter(UDF):
    """Split text into chunks of [min_tokens, max_tokens] tokens, preferring
    to cut just after sentence punctuation (reference: splitters.py:34).

    Example:

    >>> from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter
    >>> sp = TokenCountSplitter(min_tokens=2, max_tokens=6)
    >>> [c for c, _meta in sp.__wrapped__(
    ...     "One two three. Four five six seven eight. Nine.")]
    ['One two three.', 'Four five six seven eight.', 'Nine.']
    """

    def __init__(
        self,
        min_tokens: int = 50,
        max_tokens: int = 500,
        encoding_name: str = "cl100k_base",
    ):
        super().__init__(deterministic=True)
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        self.encoding_name = encoding_name

    def __wrapped__(self, txt: str, **kwargs) -> list[tuple[str, dict]]:
        text = _normalize(coerce_str(txt))
        spans = _token_spans(text, self.encoding_name)
        if not spans:
            return []
        ends = [e for _, e in spans]
        output: list[tuple[str, dict]] = []
        i = 0
        while i < len(spans):
            window = spans[i : i + self.max_tokens]
            chunk_start = window[0][0]
            chunk_end = window[-1][1]
            cut = chunk_end
            if i + self.max_tokens < len(spans):
                # last punctuation cut point keeping >= min_tokens tokens
                best = -1
                for m in _CUT_RE.finditer(text, chunk_start, chunk_end):
                    n_tokens = bisect.bisect_right(ends, m.end()) - i
                    if n_tokens >= self.min_tokens:
                        best = m.end()
                if best > 0:
                    cut = best
            piece = text[chunk_start:cut].strip()
            if piece:
                output.append((piece, {}))
            consumed = bisect.bisect_right(ends, cut) - i
            i += max(consumed, 1)
        return output


def _normalize(text: str) -> str:
    return re.sub(r"\n{3,}", "\n\n", text.replace("\r\n", "\n"))
