"""DocumentStore — the retriever-pluggable document pipeline.

reference: python/pathway/xpacks/llm/document_store.py —
``DocumentStore``:32 (pluggable ``retriever_factory``:52-64,
``build_pipeline``:286, ``retrieve_query``:426 via
``DataIndex.query_as_of_now``), ``SlidesDocumentStore``:471.

Same pipeline as VectorStoreServer but the index is built from any
``InnerIndexFactory`` (brute-force/usearch-parity HBM KNN, LSH, BM25,
hybrid) — so full-text and hybrid retrieval serve from the same engine
graph.
"""

from __future__ import annotations

from typing import Any, Callable

from ...internals import dtype as dt
from ...internals import reducers
from ...internals.expression import ApplyExpression
from ...internals.schema import Schema, column_definition
from ...internals.table import Table
from ...internals.thisclass import right
from ...internals.udfs import udf
from ...internals.value import Json
from ...stdlib.indexing.data_index import DataIndex
from ._utils import coerce_str
from .parsers import Utf8Parser
from .splitters import null_splitter
from ._pipeline import build_document_pipeline
from .vector_store import (
    InputsQuerySchema,
    RetrieveQuerySchema,
    StatisticsQuerySchema,
    _merge_filters,
)

__all__ = ["DocumentStore", "SlidesDocumentStore"]


class DocumentStore:
    """reference: document_store.py:32"""

    class RetrieveQuerySchema(RetrieveQuerySchema):
        pass

    class StatisticsQuerySchema(StatisticsQuerySchema):
        pass

    class InputsQuerySchema(InputsQuerySchema):
        pass

    class QueryResultSchema(Schema):
        result: Json

    class InputResultSchema(Schema):
        result: Json

    def __init__(
        self,
        docs: Table | list[Table],
        retriever_factory: Any,
        parser: Callable | None = None,
        splitter: Callable | None = None,
        doc_post_processors: list[Callable] | None = None,
        mesh: Any = None,
    ):
        self.docs = [docs] if isinstance(docs, Table) else list(docs)
        if mesh is None:
            # env-default multi-chip serving (PATHWAY_SERVING_MESH) —
            # same knob as VectorStoreServer
            from ...parallel.mesh import serving_mesh

            mesh = serving_mesh()
        if mesh is not None:
            # device-mesh knob: row-shard any KNN retriever over the mesh
            # (parallel/index.py) — applied to every sub-factory of a
            # hybrid factory too, when it exposes an unset ``mesh``
            # field.  Caller-owned factory objects are copied, not
            # mutated, so reuse with another server keeps its own mesh.
            import copy
            import dataclasses as _dc

            from ._utils import seed_embedder_mesh

            subs = getattr(retriever_factory, "retriever_factories", None)
            if subs is not None:
                retriever_factory = copy.copy(retriever_factory)
                retriever_factory.retriever_factories = [
                    _dc.replace(f, mesh=mesh)
                    if getattr(f, "mesh", "-") is None
                    else f
                    for f in subs
                ]
                meshed = retriever_factory.retriever_factories
            elif getattr(retriever_factory, "mesh", "-") is None:
                retriever_factory = _dc.replace(retriever_factory, mesh=mesh)
                meshed = [retriever_factory]
            else:
                meshed = []
            # same knob, same reach as VectorStoreServer: an unbuilt
            # model-backed embedder on a sharded KNN factory encodes
            # data-parallel over the mesh too
            for f in meshed:
                if getattr(f, "mesh", None) is mesh:
                    seed_embedder_mesh(getattr(f, "embedder", None), mesh)
        self.mesh = mesh
        self.retriever_factory = retriever_factory
        self.parser = parser if parser is not None else Utf8Parser()
        self.splitter = splitter if splitter is not None else null_splitter
        self.doc_post_processors = [
            p for p in (doc_post_processors or []) if p is not None
        ]
        self.build_pipeline()

    def build_pipeline(self) -> None:
        """reference: document_store.py:286 — shared pipeline + pluggable
        retriever factory."""
        graph = build_document_pipeline(
            self.docs, self.parser, self.splitter, self.doc_post_processors
        )
        self.input_docs = graph["docs"]
        self.parsed_docs = graph["parsed_docs"]
        self.chunked_docs = graph["chunked_docs"]
        self.stats = graph["stats"]
        from ...stdlib.indexing.hybrid_index import HybridIndex, HybridIndexFactory

        def make_index(factory):
            return DataIndex(
                self.chunked_docs,
                factory,
                data_column=self.chunked_docs.text,
                metadata_column=self.chunked_docs.metadata,
                embedder=getattr(factory, "embedder", None),
            )

        if isinstance(self.retriever_factory, HybridIndexFactory):
            self._retriever = HybridIndex(
                [make_index(f) for f in self.retriever_factory.retriever_factories],
                k=self.retriever_factory.k,
            )
        else:
            self._retriever = make_index(self.retriever_factory)

    @property
    def index(self) -> DataIndex:
        return self._retriever

    def scheduler_retrieve_plane(self, deadline_ms: float | None = None):
        """Fused retrieve plane for the serving scheduler, or ``None`` when
        this store cannot serve it (hybrid retriever, or a vector factory
        without an embedder).  BM25 retrievers serve text queries directly
        (no embed stage in the tick)."""
        from ...stdlib.indexing.hybrid_index import HybridIndexFactory
        from ...stdlib.indexing.retrievers import TantivyBM25Factory
        from ._scheduler import RetrievePlane

        if isinstance(self.retriever_factory, HybridIndexFactory):
            return None
        embedder = getattr(self.retriever_factory, "embedder", None)
        if embedder is None and not isinstance(
            self.retriever_factory, TantivyBM25Factory
        ):
            return None
        return RetrievePlane(
            index_factory=self.retriever_factory,
            embedder=embedder,
            payload_columns=self.chunked_docs.column_names(),
            deadline_ms=deadline_ms,
            include_score=True,
        )

    # -- queries (reference: document_store.py:426 retrieve_query) --
    def retrieve_query(self, retrieval_queries: Table) -> Table:
        queries = retrieval_queries.select(
            query=retrieval_queries.query,
            k=retrieval_queries.k,
            metadata_filter=_merge_filters(
                retrieval_queries.metadata_filter,
                retrieval_queries.filepath_globpattern,
            ),
        )
        res = self._retriever.query_as_of_now(
            queries.query,
            number_of_matches=queries.k,
            metadata_filter=queries.metadata_filter,
            collapse_rows=True,
        )

        def pack(texts, metas, scores) -> Json:
            return Json(
                [
                    {
                        "text": coerce_str(t),
                        "metadata": m.value if isinstance(m, Json) else m,
                        "score": float(s),
                        "dist": -float(s),
                    }
                    for t, m, s in zip(texts or (), metas or (), scores or ())
                ]
            )

        return res.select(
            result=ApplyExpression(
                pack,
                Json,
                right.text,
                right.metadata,
                right["_pw_index_reply_score"],
            )
        )

    def statistics_query(self, info_queries: Table) -> Table:
        def pack_stats(count, last_modified, last_indexed) -> Json:
            return Json(
                {
                    "file_count": int(count or 0),
                    "last_modified": last_modified,
                    "last_indexed": last_indexed,
                }
            )

        stats = self.stats
        return info_queries.join_left(stats, id=info_queries.id).select(
            result=ApplyExpression(
                pack_stats, Json, stats.count, stats.last_modified, stats.last_indexed
            )
        )

    def inputs_query(self, input_queries: Table) -> Table:
        docs = self.parsed_docs
        all_meta = docs.reduce(metadatas=reducers.tuple(docs.metadata))

        @udf
        def format_inputs(metadatas, metadata_filter: str | None) -> Json:
            from ...utils.jmespath_lite import compile_filter

            metas = [m.value if isinstance(m, Json) else m for m in (metadatas or ())]
            if metadata_filter:
                flt = compile_filter(metadata_filter)
                metas = [m for m in metas if flt(m)]
            return Json(metas)

        queries = input_queries.select(
            metadata_filter=_merge_filters(
                input_queries.metadata_filter, input_queries.filepath_globpattern
            )
        )
        return queries.join_left(all_meta, id=queries.id).select(
            result=format_inputs(all_meta.metadatas, queries.metadata_filter)
        )


class SlidesDocumentStore(DocumentStore):
    """Slide-deck flavor exposing the parsed-slides dump
    (reference: document_store.py:471)."""

    excluded_response_metadata = ["b64_image"]

    def parsed_documents_query(self, parse_docs_queries: Table) -> Table:
        docs = self.parsed_docs
        all_docs = docs.reduce(
            docs=reducers.tuple(
                ApplyExpression(
                    lambda t, m: Json(
                        {
                            "text": coerce_str(t),
                            "metadata": {
                                k: v
                                for k, v in (
                                    m.value if isinstance(m, Json) else m or {}
                                ).items()
                                if k not in self.excluded_response_metadata
                            },
                        }
                    ),
                    Json,
                    docs.text,
                    docs.metadata,
                )
            )
        )
        return parse_docs_queries.join_left(all_docs, id=parse_docs_queries.id).select(
            result=ApplyExpression(
                lambda ds: Json([d.value if isinstance(d, Json) else d for d in (ds or ())]),
                Json,
                all_docs.docs,
            )
        )
