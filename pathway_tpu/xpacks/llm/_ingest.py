"""Packed, pipelined ingest: tokenize → pack → encode → upsert.

The ingest plane (connector → splitter → embedder → index upsert) is
where the live-RAG loop's freshness budget goes.  This module rebuilds
its embedding hot path as a producer/consumer pipeline:

* a **host worker** tokenizes and packs (``models/encoder.pad_chunk``
  via :func:`~pathway_tpu.models.encoder.packed_prepare`) one batch
  AHEAD of the device — the double-buffered hand-off queue (depth
  ``PATHWAY_INGEST_PIPELINE_DEPTH``, default 2) means tokenize(N+1)
  overlaps encode(N) instead of serializing on the embedder thread (the
  WindVE queue-decoupling argument, arXiv:2504.14941, applied to
  ingest);
* the **device worker** transfers, encodes, and — when an index is
  attached — hands the encoder's DEVICE output straight to the staged
  scatter (``DeviceKnnIndex.upsert_batch``): the per-micro-batch
  D2H(embeddings)+H2D(same bytes) round trip disappears, only keys and
  metadata stay host-side.

Every stage records flight-recorder spans (``tokenize`` / ``h2d`` /
``encode`` / ``upsert``, category ``ingest``) and documents count into
``pathway_ingest_docs_total``; packing efficiency feeds
``pathway_embed_padding_efficiency``.  Under ``PATHWAY_FAULTS`` chaos
the device stage honors the ``embedder`` site: an injected failure
fails THAT batch's future and the pipeline keeps draining.

PR 7: with the unified device-tick runtime enabled (default,
``PATHWAY_RUNTIME=1``) the device worker no longer touches the device
itself — each prepared chunk (one bounded ``bb×seq`` launch) is
submitted to the shared executor as a ``BULK_INGEST``-class work item
whose token estimate is the chunk's padded token mass.  Interactive
serving ticks preempt the backlog at tick granularity (a query never
waits behind more than the chunk already on the device) while the
runtime's starvation bound guarantees ingest forward progress under
sustained query load.  Upsert staging (host-side bookkeeping; the
scatter itself runs at the next search) stays on the worker thread so a
failed chunk still fails its whole batch before anything is staged.
``PATHWAY_RUNTIME=0`` (or ``use_runtime=False``) restores the in-thread
device loop for A/B — the two paths are bit-identical by test.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Sequence

import numpy as np

__all__ = ["IngestPipeline", "ingest_pipeline_depth"]

_SENTINEL = object()


def ingest_pipeline_depth() -> int:
    """Prepared-batch hand-off depth (``PATHWAY_INGEST_PIPELINE_DEPTH``,
    default 2 = double-buffered: host stays exactly one batch ahead)."""
    try:
        depth = int(os.environ.get("PATHWAY_INGEST_PIPELINE_DEPTH", "2"))
    except ValueError:
        depth = 2
    return max(depth, 1)


class _Batch:
    __slots__ = ("texts", "keys", "metas", "future", "prepared", "stats")

    def __init__(self, texts, keys, metas, future):
        self.texts = texts
        self.keys = keys
        self.metas = metas
        self.future = future
        self.prepared = None
        self.stats = None


class IngestPipeline:
    """Two-stage tokenize/pack → encode/upsert pipeline over a
    :class:`~pathway_tpu.models.encoder.SentenceEncoder`.

    ``index`` (optional) is an inner index with ``add_batch`` (e.g.
    :class:`~pathway_tpu.stdlib.indexing.retrievers.BruteForceKnnIndex`)
    or a bare :class:`~pathway_tpu.ops.knn.DeviceKnnIndex`; with one
    attached, futures resolve to the number of documents upserted and
    embeddings never leave the device.  Without one, futures resolve to
    the ``[B, dim]`` float32 embeddings in submission order.
    """

    def __init__(
        self,
        encoder: Any,
        index: Any = None,
        *,
        depth: int | None = None,
        max_tokens: int | None = None,
        use_runtime: bool | None = None,
    ):
        from ...models.encoder import embed_max_tokens
        from ...runtime import WorkGroup, runtime_enabled

        self.encoder = encoder
        self.index = index
        self.depth = depth if depth is not None else ingest_pipeline_depth()
        self.max_tokens = (
            max_tokens if max_tokens is not None else embed_max_tokens()
        )
        #: device work rides the unified runtime as BULK_INGEST chunks
        #: (None = follow the global PATHWAY_RUNTIME setting)
        self.use_runtime = (
            runtime_enabled() if use_runtime is None else use_runtime
        )
        # max_batch=1: every prepared chunk is its own device dispatch
        # AND its own failure domain — one poisoned chunk must not fail
        # another pipeline batch sharing the tick
        self._encode_group = WorkGroup(
            "ingest-encode", self._encode_chunk_on_runtime, max_batch=1
        )
        self._in: queue.Queue = queue.Queue()
        # the hand-off: host worker blocks here once it is `depth`
        # batches ahead — bounded lookahead IS the backpressure
        self._ready: queue.Queue = queue.Queue(maxsize=self.depth)
        self._closed = False
        self._lock = threading.Lock()
        self._tok_thread: threading.Thread | None = None
        self._dev_thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------
    def _ensure_threads_locked(self) -> None:
        if self._tok_thread is None:
            self._tok_thread = threading.Thread(
                target=self._tokenize_loop, daemon=True,
                name="pw-ingest-tokenize",
            )
            self._dev_thread = threading.Thread(
                target=self._device_loop, daemon=True,
                name="pw-ingest-device",
            )
            self._tok_thread.start()
            self._dev_thread.start()

    def close(self) -> None:
        """Drain both stages and join the workers (idempotent)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            started = self._tok_thread is not None
        if started:
            self._in.put(_SENTINEL)
            self._tok_thread.join()
            self._dev_thread.join()

    def __enter__(self) -> "IngestPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission ------------------------------------------------------
    def submit(
        self,
        texts: Sequence[str],
        keys: Sequence[Any] | None = None,
        metas: Sequence[Any] | None = None,
    ) -> Future:
        """Enqueue one document batch.  With an index attached ``keys``
        is required (metadata optional); the future resolves once the
        batch is encoded and staged into the index."""
        if self.index is not None and keys is None:
            raise ValueError("keys are required when upserting into an index")
        if keys is not None and len(keys) != len(texts):
            raise ValueError(f"{len(keys)} keys for {len(texts)} texts")
        fut: Future = Future()
        if not texts:
            fut.set_result(
                0 if self.index is not None else np.zeros(
                    (0, self.encoder.dim), dtype=np.float32
                )
            )
            return fut
        # closed-check and enqueue under the same lock close() flips the
        # flag under — a batch can never slip in BEHIND the shutdown
        # sentinel (its future would hang forever)
        with self._lock:
            if self._closed:
                raise RuntimeError("ingest pipeline is closed")
            self._ensure_threads_locked()
            self._in.put(_Batch(list(texts), keys, metas, fut))
        return fut

    def encode(self, texts: Sequence[str]) -> Any:
        """Synchronous convenience: submit one batch and wait."""
        return self.submit(texts).result()

    # -- stage 1: host tokenize + pack ----------------------------------
    def _prepare(self, ids_all, mask_all):
        """Host half of the dispatch in the ENCODER's layout: the
        prepared-chunk protocol (``prepare_chunks``: packed (bb, seq)
        buckets or the ragged concatenated-token layout, per
        ``attention_impl``) when the encoder speaks it; the legacy
        packed_prepare shape for bare duck-typed encoders.  Either way
        every entry is ``(payload, rows, tokens)``."""
        enc = self.encoder
        prepare = getattr(enc, "prepare_chunks", None)
        if prepare is not None:
            return prepare(ids_all, mask_all, max_tokens=self.max_tokens)
        from ...models.encoder import packed_prepare

        prepared, stats = packed_prepare(
            ids_all, mask_all, enc.max_length,
            vocab_size=enc.cfg.vocab_size,
            batch_multiple=getattr(enc, "_batch_multiple", 1),
            max_tokens=self.max_tokens,
        )
        return (
            [
                ((ids, mask, tids), rows, int(np.asarray(ids).size))
                for ids, mask, tids, rows in prepared
            ],
            stats,
        )

    def _tokenize_loop(self) -> None:
        from ...internals.flight_recorder import record_span

        enc = self.encoder
        while True:
            item = self._in.get()
            if item is _SENTINEL:
                self._ready.put(_SENTINEL)
                return
            wall = time.time()
            t0 = time.monotonic()
            try:
                ids_all, mask_all = enc.tokenizer.encode_batch(
                    item.texts, max_length=enc.max_length
                )
                record_span(
                    "tokenize", "ingest", wall,
                    (time.monotonic() - t0) * 1000.0,
                    attrs={"docs": len(item.texts)},
                )
                item.prepared, item.stats = self._prepare(ids_all, mask_all)
            except BaseException as exc:  # noqa: BLE001 — fail THIS batch only
                if not item.future.done():
                    item.future.set_exception(exc)
                continue
            self._ready.put(item)  # blocks at `depth` batches ahead

    # -- stage 2: device transfer + encode + upsert ---------------------
    def _encode_chunk_on_runtime(self, payloads: list) -> list:
        """BULK_INGEST batch handler (runtime executor thread): one
        prepared chunk per call (``max_batch=1``) — H2D + encode, the
        DEVICE output returned as-is so upsert staging keeps the
        embed→upsert path device-resident.

        The chunk's device work is SYNCHRONIZED before the tick ends:
        jax dispatches are async, so returning unfinished work would
        let a bulk backlog pile into the device queue and the next
        tick's interactive dispatch would wait behind every queued
        chunk anyway — priority inversion at the device-queue level
        (observed as 300+ ms serving `search` stages behind a 64-chunk
        async backlog).  One tick in flight at a time is the executor's
        whole contract with the device."""
        assert len(payloads) == 1
        out = self._encode_chunk(payloads[0])
        import jax

        jax.block_until_ready(out)
        return [out]

    def _encode_chunk(self, payload) -> Any:
        from ...internals.flight_recorder import record_span

        enc = self.encoder
        encode_prepared = getattr(enc, "encode_prepared", None)
        wall = time.time()
        t0 = time.monotonic()
        if encode_prepared is not None:
            # the encoder's own device half: packed (bb, seq) launch or
            # ONE ragged concatenated-token launch, H2D + mesh placement
            # included (attention_impl-aware)
            out = encode_prepared(payload)
            record_span(
                "encode", "ingest", wall,
                (time.monotonic() - t0) * 1000.0,
                attrs={"tokens": int(np.asarray(payload[0]).size)
                       if isinstance(payload, tuple)
                       else int(np.asarray(payload.ids).size)},
            )
            return out
        import jax.numpy as jnp

        ids, mask, tids = payload
        args = [jnp.asarray(ids), jnp.asarray(mask)]
        if tids is not None:
            args.append(jnp.asarray(tids))
        if getattr(enc, "mesh", None) is not None:
            import jax

            # the encoder's own data-parallel rule: shard chunks that
            # divide the data axis, replicate small tails
            rule = getattr(enc, "_input_sharding", None)
            sharding = (
                rule(args[0].shape[0]) if rule is not None
                else enc._data_sharding
            )
            args = [jax.device_put(a, sharding) for a in args]
        record_span(
            "h2d", "ingest", wall, (time.monotonic() - t0) * 1000.0,
            attrs={"chunks": 1},
        )
        wall = time.time()
        t0 = time.monotonic()
        out = enc._apply(enc.params, *args)
        record_span(
            "encode", "ingest", wall, (time.monotonic() - t0) * 1000.0,
            attrs={"rows": int(np.asarray(ids).shape[0])},
        )
        return out

    def _device_loop(self) -> None:
        from ...internals.flight_recorder import (
            record_ingest_docs,
            record_padding,
            record_span,
        )

        while True:
            item = self._ready.get()
            if item is _SENTINEL:
                return
            try:
                from ...testing import faults

                if faults.enabled:
                    # chaos site "embedder": a failed encode fails this
                    # batch's future; the pipeline keeps draining
                    faults.perturb("embedder")
                record_padding(
                    item.stats["real_tokens"],
                    item.stats["padded_tokens"],
                    item.stats.get("row_tokens"),
                )
                if self.use_runtime:
                    # every prepared chunk is one BULK_INGEST work item:
                    # tokens = its padded token mass (one ragged launch
                    # == one item too), coalesce 0 (a backlog never
                    # waits for tick-mates).  Interactive ticks slot in
                    # between chunks; the min-share bound keeps this
                    # batch progressing under query floods.
                    from ...runtime import QoS, get_runtime

                    rt = get_runtime()
                    futs = [
                        (
                            rt.submit(
                                self._encode_group,
                                payload,
                                qos=QoS.BULK_INGEST,
                                tokens=int(tokens),
                                coalesce_s=0.0,
                            ),
                            rows,
                        )
                        for payload, rows, tokens in item.prepared
                    ]
                    # all chunks must encode before anything stages:
                    # a failed chunk fails the WHOLE batch pre-upsert,
                    # exactly like the legacy single-thread path
                    outs = [(f.result(), rows) for f, rows in futs]
                else:
                    outs = [
                        (self._encode_chunk(payload), rows)
                        for payload, rows, _tokens in item.prepared
                    ]
                if self.index is not None:
                    wall = time.time()
                    t0 = time.monotonic()
                    for out, rows in outs:
                        keys = [item.keys[i] for i in rows]
                        metas = (
                            [item.metas[i] for i in rows]
                            if item.metas is not None
                            else [None] * len(rows)
                        )
                        if hasattr(self.index, "add_batch"):
                            self.index.add_batch(keys, out, metas)
                        else:
                            self.index.upsert_batch(keys, out)
                    record_span(
                        "upsert", "ingest", wall,
                        (time.monotonic() - t0) * 1000.0,
                        attrs={"docs": len(item.texts)},
                    )
                    record_ingest_docs(len(item.texts))
                    result: Any = len(item.texts)
                else:
                    emb = np.empty(
                        (len(item.texts), self.encoder.dim), dtype=np.float32
                    )
                    for out, rows in outs:
                        emb[rows] = np.asarray(out, dtype=np.float32)[: len(rows)]
                    result = emb
            except BaseException as exc:  # noqa: BLE001 — fail THIS batch only
                if not item.future.done():
                    item.future.set_exception(exc)
                continue
            if not item.future.done():
                item.future.set_result(result)
