"""Continuous cross-request serving scheduler.

The engine's :class:`~pathway_tpu.xpacks.llm._utils.AsyncMicroBatcher`
coalesces only the calls that land in the *same* engine micro-batch, so
under concurrent REST load the device sees one small embed/search dispatch
per request and query p99 balloons (serving_bench: p99 ≈ 2.4× p50 on CPU).
This module decouples device batching from engine cadence the way WindVE
(arXiv:2504.14941) decouples a host-side concurrency queue from the
accelerator:

* a host-side **admission queue** collects work items (embed texts, rerank
  pairs, fused retrieve requests) from every in-flight plane — engine
  micro-batches AND concurrent REST handlers;
* a single **device-step loop** drains it on a ``max_batch`` /
  ``max_wait_ms`` policy, so one scheduler tick carries embeds from
  request A, KNN probes from request B and rerank pairs from request C,
  each kind as one padded device dispatch (the power-of-two bucketing in
  ``models/encoder.py`` / ``ops/topk.bucket_k`` keeps XLA compile counts
  flat across the ragged batch sizes this produces);
* requests carry an optional **deadline**: items whose deadline passed
  before dispatch are shed with :class:`DeadlineExceeded` (REST planes
  map it to 503 + ``Retry-After``) and their device work never runs —
  backpressure, not collapse.  Admission beyond ``max_queue`` is refused
  immediately with :class:`SchedulerOverloaded`.

Observability (queue depth, batch occupancy, wait-time histogram,
deadline drops) registers with ``internals/monitoring.py`` and renders on
the OpenMetrics ``/status`` endpoint as ``pathway_scheduler_*`` series.

PR 7: by default (``PATHWAY_RUNTIME=1``) :class:`ServingScheduler` is a
**thin facade over the unified device-tick runtime**
(:mod:`pathway_tpu.runtime`): submissions execute on the shared QoS
executor as ``INTERACTIVE`` work (so they preempt bulk-ingest chunks at
tick granularity), while this class keeps its legacy per-instance
counters, admission cap and ``pathway_scheduler_*`` series via observer
hooks.  ``PATHWAY_RUNTIME=0`` restores the self-contained device-step
loop below for A/B.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from ...runtime import (
    AdmissionRefused,
    DeadlineExceeded,
    QoS,
    WorkGroup,
    budget_chunks as _budget_chunks,
    get_runtime,
    runtime_enabled,
)

__all__ = [
    "ServingScheduler",
    "WorkGroup",
    "DeadlineExceeded",
    "SchedulerOverloaded",
    "ServingNotReady",
    "RetrievePlane",
    "get_scheduler",
    "configure",
    "scheduler_enabled",
    "serving_settings",
]


#: admission refused: the queue is at capacity (the runtime's exception,
#: kept under its historical serving name)
SchedulerOverloaded = AdmissionRefused


class ServingNotReady(DeadlineExceeded):
    """The live index is not lowered yet (engine still starting up)."""


class _WorkItem:
    __slots__ = (
        "group", "payload", "future", "enqueued_at", "deadline_at", "trace",
    )

    def __init__(self, group, payload, future, enqueued_at, deadline_at,
                 trace=None):
        self.group = group
        self.payload = payload
        self.future = future
        self.enqueued_at = enqueued_at
        self.deadline_at = deadline_at
        #: sampled RequestTrace riding this item (internals/flight_recorder)
        self.trace = trace


#: wait-time histogram bucket upper bounds (milliseconds)
_WAIT_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0)


class ServingScheduler:
    """Admission queue + device-step loop (see module docstring)."""

    def __init__(
        self,
        *,
        max_batch: int = 256,
        max_wait_ms: float = 2.0,
        max_queue: int = 1024,
        retry_after_s: float = 1.0,
        name: str = "serving",
    ):
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.max_queue = max_queue
        self.retry_after_s = retry_after_s
        self.name = name
        self._cv = threading.Condition()
        self._queue: list[_WorkItem] = []
        self._thread: threading.Thread | None = None
        #: facade mode: items currently enqueued on the shared runtime
        #: on this scheduler's behalf (legacy queue-depth/admission view)
        self._runtime_pending = 0
        # metrics — guarded by _mx, not _cv: the tick updates them while
        # submitters hold _cv
        self._mx = threading.Lock()
        self._counters = {
            "submitted_total": 0,
            "completed_total": 0,
            "failed_total": 0,
            "shed_deadline_total": 0,
            "shed_queue_total": 0,
            "batches_total": 0,
            "multi_item_batches_total": 0,
        }
        self._occupancy_sum = 0
        self._occupancy_max = 0
        self._queue_depth_max = 0
        self._wait_buckets = [0] * (len(_WAIT_BUCKETS_MS) + 1)
        self._wait_sum_ms = 0.0
        self._wait_count = 0
        from ...internals.monitoring import register_metrics_provider

        register_metrics_provider(name, self)

    # -- submission ------------------------------------------------------
    def submit(
        self,
        group: WorkGroup,
        payload: Any,
        *,
        deadline_s: float | None = None,
        sheddable: bool | None = None,
        trace: Any = None,
    ) -> Future:
        """Enqueue one payload; the future resolves when its batch ran.

        ``deadline_s`` is a relative budget: if the item is still queued
        that long after submission it is shed with :class:`DeadlineExceeded`
        and its work never executes.  ``None`` (engine-plane work) is
        never shed.

        ``sheddable`` work (default: anything with a deadline; serving
        planes pass True explicitly) is additionally subject to
        ``max_queue`` admission control.  Engine-plane work is exempt:
        refusing an ingest micro-batch's embeds would error the engine,
        and its volume is already bounded by engine batch sizes.

        ``trace`` (a sampled ``RequestTrace``) rides the item: the drain
        stamps its queue wait and the batch handler's stage timers
        (embed, search) attribute device time back to the request.
        """
        if sheddable is None:
            sheddable = deadline_s is not None
        if trace is not None and not trace.sampled:
            trace = None
        if runtime_enabled():
            # facade path: execute on the unified QoS runtime as
            # INTERACTIVE work.  This scheduler keeps its legacy
            # admission cap (max_queue over ITS OWN pending items) and
            # its pathway_scheduler_* counters via the observer hooks
            # below; re-entrant submits from the runtime thread are
            # handled by the runtime itself (inline, inheriting the
            # running tick's class — no class inversion, no deadlock).
            rt = get_runtime()
            if (
                sheddable
                and not rt.on_runtime_thread()
                and self._runtime_pending >= self.max_queue
            ):
                with self._mx:
                    self._counters["shed_queue_total"] += 1
                fut: Future = Future()
                fut.set_exception(
                    SchedulerOverloaded(
                        f"scheduler queue full ({self.max_queue} pending)",
                        retry_after_s=self.retry_after_s,
                    )
                )
                return fut
            with self._mx:
                self._counters["submitted_total"] += 1
            return rt.submit(
                group,
                payload,
                qos=QoS.INTERACTIVE,
                deadline_s=deadline_s,
                sheddable=sheddable,
                trace=trace,
                coalesce_s=self.max_wait_ms / 1000.0,
                observer=self,
                retry_after_s=self.retry_after_s,
            )
        fut: Future = Future()
        if self._thread is not None and threading.current_thread() is self._thread:
            # re-entrant submit from inside a batch handler (e.g. a
            # retrieve handler whose embedder delegates through the
            # batcher): run inline — a queued item could never drain
            # while the loop is inside this very tick.  _execute handles
            # the dispatch lock, result validation and error routing
            self._execute(
                group,
                [_WorkItem(group, payload, fut, time.monotonic(), None, trace)],
            )
            return fut
        now = time.monotonic()
        item = _WorkItem(
            group,
            payload,
            fut,
            now,
            None if deadline_s is None else now + deadline_s,
            trace,
        )
        with self._cv:
            if sheddable and len(self._queue) >= self.max_queue:
                with self._mx:
                    self._counters["shed_queue_total"] += 1
                fut.set_exception(
                    SchedulerOverloaded(
                        f"scheduler queue full ({self.max_queue} pending)",
                        retry_after_s=self.retry_after_s,
                    )
                )
                return fut
            self._ensure_thread()
            self._queue.append(item)
            depth = len(self._queue)
            self._cv.notify_all()
        with self._mx:
            self._counters["submitted_total"] += 1
            if depth > self._queue_depth_max:
                self._queue_depth_max = depth
        return fut

    async def submit_async(
        self,
        group: WorkGroup,
        payload: Any,
        *,
        deadline_s: float | None = None,
        sheddable: bool | None = None,
        trace: Any = None,
    ) -> Any:
        return await asyncio.wrap_future(
            self.submit(
                group, payload,
                deadline_s=deadline_s, sheddable=sheddable, trace=trace,
            )
        )

    def executor_alive(self) -> bool:
        """Is the device-step executor serving this scheduler alive?
        Facade mode: the shared runtime's tick thread; legacy mode: this
        scheduler's own loop thread.  (The containment tests' "the loop
        survived the fault" observable, architecture-neutral.)"""
        if runtime_enabled():
            rt = get_runtime()
            return rt._thread is not None and rt._thread.is_alive()
        return self._thread is not None and self._thread.is_alive()

    # -- runtime observer hooks (facade mode) ----------------------------
    # The shared runtime calls these (never under its condition variable)
    # so this scheduler's legacy per-instance counters — queue depth,
    # wait histogram, occupancy, shed/completed/failed — stay truthful
    # while the actual draining happens on the unified executor.
    def _obs_enqueued(self) -> None:
        with self._mx:
            self._runtime_pending += 1
            if self._runtime_pending > self._queue_depth_max:
                self._queue_depth_max = self._runtime_pending

    def _obs_drained(self) -> None:
        with self._mx:
            self._runtime_pending -= 1

    def _obs_wait(self, wait_ms: float) -> None:
        self._observe_wait(wait_ms)

    def _obs_shed_deadline(self) -> None:
        with self._mx:
            self._counters["shed_deadline_total"] += 1

    def _obs_refused(self) -> None:
        with self._mx:
            self._counters["shed_queue_total"] += 1

    def _obs_batch(self, n: int) -> None:
        with self._mx:
            self._counters["batches_total"] += 1
            if n > 1:
                self._counters["multi_item_batches_total"] += 1
            self._occupancy_sum += n
            if n > self._occupancy_max:
                self._occupancy_max = n

    def _obs_done(self, n: int, ok: bool) -> None:
        with self._mx:
            self._counters["completed_total" if ok else "failed_total"] += n

    # -- device-step loop (legacy, PATHWAY_RUNTIME=0) --------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name=f"pw-scheduler-{self.name}"
            )
            self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue:
                    self._cv.wait()
                # admission window: from the first pending item, wait up
                # to max_wait_ms for concurrent requests to join the tick,
                # flushing early once max_batch items are pending
                flush_at = time.monotonic() + self.max_wait_ms / 1000.0
                while len(self._queue) < self.max_batch:
                    remaining = flush_at - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                items, self._queue = self._queue, []
            try:
                self._run_tick(items)
            except BaseException as exc:  # noqa: BLE001 — the loop must
                # survive; per-item errors are already routed to futures in
                # _execute, so anything landing here is a harness bug: fail
                # the unresolved items with the ACTUAL exception (a generic
                # wrapper would make the defect undiagnosable)
                for it in items:
                    if not it.future.done():
                        it.future.set_exception(exc)

    def _run_tick(self, items: list[_WorkItem]) -> None:
        now = time.monotonic()
        groups: dict[int, tuple[WorkGroup, list[_WorkItem]]] = {}
        for it in items:  # submission order preserved: results must zip
            groups.setdefault(id(it.group), (it.group, []))[1].append(it)
        for group, gitems in groups.values():
            live: list[_WorkItem] = []
            for it in gitems:
                self._observe_wait((now - it.enqueued_at) * 1000.0)
                if it.trace is not None:
                    it.trace.add_stage_mono("queue_wait", it.enqueued_at, now)
                if it.deadline_at is not None and now > it.deadline_at:
                    with self._mx:
                        self._counters["shed_deadline_total"] += 1
                    if not it.future.done():  # client may have cancelled
                        it.future.set_exception(
                            DeadlineExceeded(
                                "deadline exceeded before dispatch "
                                f"(queued {(now - it.enqueued_at) * 1000:.1f} ms)",
                                retry_after_s=self.retry_after_s,
                            )
                        )
                else:
                    live.append(it)
            for chunk in _budget_chunks(group, live):
                self._execute(group, chunk)

    def _execute(self, group: WorkGroup, chunk: list[_WorkItem]) -> None:
        if not chunk:
            return
        from ...internals.flight_recorder import batch_traces, record_span

        with self._mx:
            self._counters["batches_total"] += 1
            if len(chunk) > 1:
                self._counters["multi_item_batches_total"] += 1
            self._occupancy_sum += len(chunk)
            if len(chunk) > self._occupancy_max:
                self._occupancy_max = len(chunk)
        # honor the batcher's dispatch lock: build-time probes may call the
        # model off-thread while the loop runs
        lock = getattr(group, "_dispatch_lock", None)
        traces = [it.trace for it in chunk if it.trace is not None]
        tick_wall = time.time()
        tick_t0 = time.monotonic()
        ok = True
        try:
            from ...testing import faults

            if faults.enabled:
                # chaos site "scheduler.step": a failed device step fans
                # out to the batch's waiters like any handler error
                faults.perturb("scheduler.step")
            # batch-scope the riding traces: the handler's stage timers
            # (embed, search) stamp onto every request in the tick
            with batch_traces(traces):
                if lock is not None:
                    with lock:
                        results = group.batch_fn([it.payload for it in chunk])
                else:
                    results = group.batch_fn([it.payload for it in chunk])
            if len(results) != len(chunk):
                raise RuntimeError(
                    f"batch handler {group.label!r} returned {len(results)} "
                    f"results for {len(chunk)} items"
                )
        except BaseException as exc:  # noqa: BLE001 — propagate to every waiter
            ok = False
            with self._mx:
                self._counters["failed_total"] += len(chunk)
            for it in chunk:
                if not it.future.done():
                    it.future.set_exception(exc)
            return
        finally:
            record_span(
                f"tick:{group.label}",
                "scheduler",
                tick_wall,
                (time.monotonic() - tick_t0) * 1000.0,
                attrs={
                    "scheduler": self.name,
                    "occupancy": len(chunk),
                    "ok": ok,
                },
            )
        with self._mx:
            self._counters["completed_total"] += len(chunk)
        for it, res in zip(chunk, results):
            if not it.future.done():
                it.future.set_result(res)

    def _observe_wait(self, wait_ms: float) -> None:
        with self._mx:
            self._wait_sum_ms += wait_ms
            self._wait_count += 1
            for i, le in enumerate(_WAIT_BUCKETS_MS):
                if wait_ms <= le:
                    self._wait_buckets[i] += 1
                    break
            else:
                self._wait_buckets[-1] += 1

    # -- observability ---------------------------------------------------
    def stats(self) -> dict[str, Any]:
        with self._cv:
            depth = len(self._queue)
        with self._mx:
            # facade mode: pending items live on the shared runtime's
            # interactive queue, tracked per scheduler via the hooks
            depth += self._runtime_pending
            batches = self._counters["batches_total"]
            return {
                **self._counters,
                "queue_depth": depth,
                "queue_depth_max": self._queue_depth_max,
                "batch_occupancy_mean": (
                    self._occupancy_sum / batches if batches else 0.0
                ),
                "batch_occupancy_max": self._occupancy_max,
                "wait_ms_sum": self._wait_sum_ms,
                "wait_ms_count": self._wait_count,
                "wait_ms_buckets": [
                    (le, n)
                    for le, n in zip(
                        (*_WAIT_BUCKETS_MS, float("inf")), self._wait_buckets
                    )
                ],
            }

    def openmetrics_lines(self) -> list[str]:
        """``pathway_scheduler_*`` series for the /status endpoint."""
        from ...internals.metrics_names import escape_label_value

        s = self.stats()
        lbl = f'scheduler="{escape_label_value(self.name)}"'
        lines = []
        for metric, kind in (
            ("submitted_total", "counter"),
            ("completed_total", "counter"),
            ("failed_total", "counter"),
            ("shed_deadline_total", "counter"),
            ("shed_queue_total", "counter"),
            ("batches_total", "counter"),
            ("multi_item_batches_total", "counter"),
            ("queue_depth", "gauge"),
            ("queue_depth_max", "gauge"),
            ("batch_occupancy_max", "gauge"),
        ):
            lines.append(f"# TYPE pathway_scheduler_{metric} {kind}")
            lines.append(f"pathway_scheduler_{metric}{{{lbl}}} {s[metric]}")
        lines.append("# TYPE pathway_scheduler_batch_occupancy_mean gauge")
        lines.append(
            f"pathway_scheduler_batch_occupancy_mean{{{lbl}}} "
            f"{s['batch_occupancy_mean']:.3f}"
        )
        lines.append("# TYPE pathway_scheduler_wait_ms histogram")
        cum = 0
        for le, n in s["wait_ms_buckets"]:
            cum += n
            le_s = "+Inf" if le == float("inf") else f"{le:g}"
            lines.append(
                f'pathway_scheduler_wait_ms_bucket{{{lbl},le="{le_s}"}} {cum}'
            )
        lines.append(
            f"pathway_scheduler_wait_ms_sum{{{lbl}}} {s['wait_ms_sum']:.3f}"
        )
        lines.append(
            f"pathway_scheduler_wait_ms_count{{{lbl}}} {s['wait_ms_count']}"
        )
        return lines


# ---------------------------------------------------------------------------
# process-global scheduler + settings
# ---------------------------------------------------------------------------


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "false", "off", "no", "")


_SETTINGS: dict[str, Any] = {
    "enabled": _env_flag("PATHWAY_SERVING_SCHEDULER", True),
    "max_batch": int(os.environ.get("PATHWAY_SERVING_MAX_BATCH", "256")),
    # 5 ms absorbs the few-ms arrival stagger of a burst (e.g. responses
    # of one tick fanning back out through HTTP and returning) so bursts
    # stay coalesced instead of splitting into alternating half-full
    # ticks; singleton queries pay at most this much extra
    "max_wait_ms": float(os.environ.get("PATHWAY_SERVING_MAX_WAIT_MS", "5.0")),
    "max_queue": int(os.environ.get("PATHWAY_SERVING_MAX_QUEUE", "1024")),
    "deadline_ms": (
        float(os.environ["PATHWAY_SERVING_DEADLINE_MS"])
        if os.environ.get("PATHWAY_SERVING_DEADLINE_MS")
        else None
    ),
    "retry_after_s": float(os.environ.get("PATHWAY_SERVING_RETRY_AFTER_S", "1.0")),
}
_GLOBAL_LOCK = threading.Lock()
_GLOBAL: ServingScheduler | None = None


def scheduler_enabled() -> bool:
    return bool(_SETTINGS["enabled"])


def serving_settings() -> dict[str, Any]:
    return dict(_SETTINGS)


def configure(**kwargs: Any) -> None:
    """Adjust the global serving policy (``enabled``, ``max_batch``,
    ``max_wait_ms``, ``max_queue``, ``deadline_ms``, ``retry_after_s``).
    Live knobs apply to the already-running global scheduler too."""
    unknown = set(kwargs) - set(_SETTINGS)
    if unknown:
        raise TypeError(f"unknown serving settings: {sorted(unknown)}")
    _SETTINGS.update(kwargs)
    with _GLOBAL_LOCK:
        sched = _GLOBAL
    if sched is not None:
        for knob in ("max_batch", "max_wait_ms", "max_queue", "retry_after_s"):
            if knob in kwargs:
                setattr(sched, knob, kwargs[knob])


def get_scheduler() -> ServingScheduler:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ServingScheduler(
                max_batch=_SETTINGS["max_batch"],
                max_wait_ms=_SETTINGS["max_wait_ms"],
                max_queue=_SETTINGS["max_queue"],
                retry_after_s=_SETTINGS["retry_after_s"],
            )
        return _GLOBAL


# ---------------------------------------------------------------------------
# fused retrieve plane (embed → KNN in one scheduler tick)
# ---------------------------------------------------------------------------


def _encode_under_dispatch_lock(embedder, encode_fn, texts: list[str]):
    """Run one model encode holding the batcher's dispatch lock: with a
    mixed configuration (e.g. use_scheduler=False on the embedder)
    engine-plane encodes run off this thread under the same lock, and the
    model is not thread-safe across concurrent callers.  The one lock
    contract for both the host and the fused device embed paths."""
    from ._utils import coerce_str

    batcher = getattr(embedder, "_batcher", None)
    lock = getattr(batcher, "_dispatch_lock", None)
    coerced = [coerce_str(t) for t in texts]
    if lock is not None:
        with lock:
            return encode_fn(coerced)
    return encode_fn(coerced)


def _batch_embed(embedder, texts: list[str]):
    """One padded device dispatch for a batch of query texts.

    Model-backed embedders expose their underlying encoder
    (``_ensure_encoder``) — calling it directly keeps the embeddings as
    one device array handed straight to the index search (the fused
    path) AND avoids re-entering the scheduler from its own thread.
    Generic UDF embedders fall back to per-text calls.
    """
    from ._utils import coerce_str

    ensure = getattr(embedder, "_ensure_encoder", None)
    if ensure is not None:
        enc = ensure()
        return _encode_under_dispatch_lock(embedder, enc.encode, texts)
    from .embedders import _call_sync

    fn = getattr(embedder, "__wrapped__", embedder)
    return np.stack(
        [np.asarray(_call_sync(fn, coerce_str(t))).reshape(-1) for t in texts]
    )


def _batch_embed_device(embedder, texts: list[str]):
    """Device-resident variant of :func:`_batch_embed` for the fused
    embed→search tick: ONE whole-batch launch whose device output is
    handed straight to the index search (``SentenceEncoder.encode_padded``
    — rows past ``len(texts)`` are dispatch pads the search discards by
    construction).  Returns ``None`` when the embedder has no model-backed
    encoder or the batch falls outside the padded dispatch's envelope —
    callers fall back to the host path.  ``PATHWAY_FUSED_SERVING=0``
    disables the device handoff for A/B runs (the host path is the
    pre-PR8 behavior: embeddings round-trip D2H then re-stage H2D for
    the search — one extra wire round trip per tick on a remote chip)."""
    if not _env_flag("PATHWAY_FUSED_SERVING", True):
        return None
    ensure = getattr(embedder, "_ensure_encoder", None)
    if ensure is None:
        return None
    enc = ensure()
    encode_padded = getattr(enc, "encode_padded", None)
    if encode_padded is None:
        return None
    try:
        embs, _n = _encode_under_dispatch_lock(
            embedder, encode_padded, texts
        )
    except ValueError:
        return None  # outside the dispatch buckets — host path handles it
    import jax.numpy as jnp

    from ...ops.fused_serving import record_launch, serving_wire_dtype

    if serving_wire_dtype() == "bf16" and embs.dtype == jnp.float32:
        # bf16-on-the-wire (the serving default): half the bytes on the
        # encoder→search handoff.  The fused search and the query-cache
        # combine both widen back to f32 in-register before any
        # normalization or cache fill — bf16→f32 is exact, so scores
        # and cache hit/miss bit-exactness are unchanged
        # (PATHWAY_SERVING_WIRE_DTYPE=f32 opts out, see MIGRATION).
        embs = embs.astype(jnp.bfloat16)
        record_launch("wire")
    return embs


class _LexicalMirror:
    """Degraded-mode lexical fallback: a host-side BM25 index (the same
    scoring the hybrid index's lexical side uses,
    ``stdlib/indexing/retrievers.BM25Index``) mirrored lazily from the
    live index node's doc payloads.  When the embedder breaker is open,
    ``/v1/retrieve`` answers from here — wrong ranking beats no answer
    for a RAG service (EdgeRAG, arXiv 2412.21023)."""

    def __init__(self, text_i: int, meta_i: int):
        from ...stdlib.indexing.retrievers import BM25Index
        from ...internals.value import Json

        self._Json = Json
        self._bm25 = BM25Index()
        self._text_i = text_i
        self._meta_i = meta_i
        self._have: set = set()
        self._lock = threading.Lock()

    def _sync(self, node) -> None:
        # dict(d) is one C-level copy under the GIL — safe against the
        # engine thread mutating doc_payload mid-snapshot
        snap = dict(node.doc_payload)
        with self._lock:
            for key in self._have - snap.keys():
                self._bm25.remove(key)
            for key, payload in snap.items():
                if key in self._have:
                    continue
                meta = payload[self._meta_i]
                if isinstance(meta, self._Json):
                    meta = meta.value
                from ._utils import coerce_str

                self._bm25.add(key, coerce_str(payload[self._text_i]), meta)
            self._have = set(snap)

    def search(self, node, items: list[tuple[str, int, str | None]]):
        self._sync(node)
        return self._bm25.search(list(items))


class RetrievePlane:
    """Scheduler-served ``/v1/retrieve``: concurrent REST requests coalesce
    into one fused embed→search tick over the LIVE index (the engine keeps
    maintaining it; queries no longer ride engine micro-batch cadence).

    Answers are as-of-now: each batch reads the index's current state
    under its own lock, the same contract ``query_as_of_now`` serves.

    Failure domain: consecutive embed failures trip ``breaker`` (a
    :class:`~pathway_tpu.xpacks.llm._breaker.CircuitBreaker`); while it is
    open, queries are served from the BM25 lexical mirror and responses
    carry ``"degraded": true`` instead of 5xx-ing.  A half-open probe
    batch restores the vector path automatically once the embedder heals.
    """

    def __init__(
        self,
        *,
        index_factory: Any,
        embedder: Any,
        payload_columns: list[str],
        scheduler: ServingScheduler | None = None,
        deadline_ms: float | None = None,
        include_score: bool = False,
        max_batch: int | None = None,
        label: str = "retrieve",
        breaker: Any = None,
        lexical_fallback: bool = True,
    ):
        self.scheduler = scheduler if scheduler is not None else get_scheduler()
        self.index_factory = index_factory
        self.embedder = embedder
        self.include_score = include_score
        self._deadline_ms_override = deadline_ms
        self._text_i = payload_columns.index("text")
        self._meta_i = payload_columns.index("metadata")
        if breaker is None and embedder is not None:
            from ._breaker import CircuitBreaker

            breaker = CircuitBreaker(f"embedder:{label}")
        self.breaker = breaker
        self._mirror = (
            _LexicalMirror(self._text_i, self._meta_i)
            if lexical_fallback
            else None
        )
        if max_batch is None:
            max_batch = self.scheduler.max_batch
        from ._utils import estimate_tokens

        # token estimate = the query text's mass: the runtime's tick
        # budget then sees retrieve work at the same scale as embed work
        self.group = WorkGroup(
            label,
            self._batch,
            max_batch=max_batch,
            token_estimate=lambda payload: estimate_tokens(payload[0]),
        )
        # serving cache stack (xpacks/llm/_query_cache): embedding +
        # result caches and the collaborative CPU embed path, built
        # lazily on first healthy batch so env knobs read at serve time
        self._query_cache_stack = None
        self._query_cache_tried = False
        self._query_cache_build_logged = False
        self._refresh_group: WorkGroup | None = None

    @property
    def deadline_ms(self) -> float | None:
        """Per-plane override, else the LIVE global setting — so
        ``configure(deadline_ms=...)`` applies to running servers too."""
        if self._deadline_ms_override is not None:
            return self._deadline_ms_override
        return _SETTINGS["deadline_ms"]

    # -- batch handler (scheduler thread) --
    def _batch(
        self, items: list[tuple[str, int, str | None]]
    ) -> list[dict]:
        from ...stdlib.indexing.lowering import live_index_node

        node = live_index_node(self.index_factory)
        if node is None:
            raise ServingNotReady(
                "index is not serving yet (engine starting)",
                retry_after_s=self.scheduler.retry_after_s,
            )
        index = node.index
        # warm-restart health gate, checked BEFORE any index read: while
        # the driver streams snapshot chunks back in, results come from
        # half-restored state and must never be presented as authoritative
        restoring = getattr(node, "_restore_state", None) == "restoring"
        if getattr(index, "query_is_text", False):
            from ...internals.flight_recorder import batch_stage as _bs

            # a restoring lexical index still answers (restore is
            # host-side and monotone) but the reply is tagged degraded —
            # partial results, not authoritative ones
            with _bs("search"):
                raw = index.search(list(items))
            return [
                {"results": self._pack(node, row), "degraded": restoring}
                for row in raw
            ]
        from ...internals.flight_recorder import batch_stage

        # vector path while restoring: answer from the lexical mirror
        # (tagged degraded) until the restored frontier catches the
        # commit record, never 503
        if restoring:
            if self._mirror is None:
                raise ServingNotReady(
                    "index is restoring from snapshot",
                    retry_after_s=self.scheduler.retry_after_s,
                )
            with batch_stage("lexical_search"):
                raw = self._mirror.search(node, items)
            return [
                {"results": self._pack(node, row), "degraded": True}
                for row in raw
            ]
        if self.embedder is None:
            raise RuntimeError(
                "retrieve plane needs an embedder for a vector index"
            )
        raw = None
        if self.breaker is None or self.breaker.allow():
            try:
                from ...testing import faults

                if faults.enabled:
                    faults.perturb("embedder")
                texts = [q for q, _, _ in items]
                specs = [(k, flt) for _, k, flt in items]
                stack = self._cache_stack()
                # the cache stack fronts only the fully-healthy fused
                # path: a half-open breaker's probe batch must actually
                # probe the device (a cache hit would "heal" a dead
                # embedder), and custom indexes without search_embedded
                # keep the legacy per-row path
                use_stack = (
                    stack is not None
                    and hasattr(index, "search_embedded")
                    and getattr(node, "commit_seq", None) is not None
                    and (self.breaker is None or self.breaker.state == "closed")
                )
                if use_stack:
                    raw = stack.serve(self, node, index, texts, specs, items)
                else:
                    with batch_stage("embed"):
                        # fused handoff: keep the tick's embeddings ON
                        # DEVICE between encode and search when the index
                        # consumes whole-batch queries (search discards
                        # the dispatch pad rows; the sharded index
                        # replicates the batch across the mesh and merges
                        # per-shard top-k over ICI)
                        embs = None
                        if hasattr(index, "search_embedded"):
                            embs = _batch_embed_device(self.embedder, texts)
                        if embs is None:
                            embs = _batch_embed(self.embedder, texts)
                    with batch_stage("search"):
                        if hasattr(index, "search_embedded"):
                            raw = index.search_embedded(embs, specs)
                        else:
                            raw = index.search(
                                [(embs[i], k, flt) for i, (k, flt) in enumerate(specs)]
                            )
            except Exception as exc:  # noqa: BLE001 — degrade, don't 5xx
                # record FIRST: even without a fallback the breaker must
                # trip so repeated failures fail fast (ServingNotReady)
                # instead of paying the full embed timeout per request
                if self.breaker is not None:
                    self.breaker.record_failure(exc)
                from ...internals.errors import register_error

                register_error(
                    f"serving embed/search failed, degrading to lexical: "
                    f"{type(exc).__name__}: {exc}",
                    kind="serving",
                    operator=self.group.label,
                )
                # device-fault containment: a FATAL device error (HBM
                # OOM, XLA runtime error, dead transfer) means the index
                # arrays are suspect — rebuild them from the host mirror
                # / snapshot now, so the breaker's half-open probe runs
                # against healthy buffers instead of re-tripping forever
                from ...ops.device_faults import FATAL, classify_device_error

                if classify_device_error(exc) == FATAL and hasattr(
                    node, "rebuild_device_state"
                ):
                    try:
                        node.rebuild_device_state()
                    except Exception as rexc:  # noqa: BLE001 — degraded
                        register_error(
                            f"index rebuild after device fault failed: "
                            f"{type(rexc).__name__}: {rexc}",
                            kind="serving",
                            operator=self.group.label,
                        )
                if self.breaker is None or self._mirror is None:
                    raise
            else:
                if self.breaker is not None:
                    self.breaker.record_success()
        if raw is not None:
            return [
                {"results": self._pack(node, row), "degraded": False}
                for row in raw
            ]
        # degraded path: breaker open (or this batch just tripped it) —
        # lexical BM25 over the live doc payloads, tagged degraded
        if self._mirror is None:
            raise ServingNotReady(
                "embedder unavailable and lexical fallback disabled",
                retry_after_s=self.scheduler.retry_after_s,
            )
        with batch_stage("lexical_search"):
            raw = self._mirror.search(node, items)
        return [
            {"results": self._pack(node, row), "degraded": True}
            for row in raw
        ]

    # -- serving cache stack (xpacks/llm/_query_cache) -------------------
    def _cache_stack(self):
        """The plane's cache stack, built once (None when every layer is
        disabled or the embedder can't be keyed).  A build failure (e.g.
        the embedder's lazy model load hiccuping) must neither ride the
        serving tick's except — a cache is an optimization, charging the
        breaker for it would degrade a healthy device — nor latch: the
        tried-flag is set only on success, so the next batch retries
        (the same lazy load _batch_embed is about to do anyway)."""
        if not self._query_cache_tried:
            from ._query_cache import build_stack

            try:
                self._query_cache_stack = build_stack(
                    self.embedder, label=self.group.label
                )
            except Exception as exc:  # noqa: BLE001 — cache is optional
                if not self._query_cache_build_logged:
                    self._query_cache_build_logged = True
                    from ...internals.errors import register_error

                    register_error(
                        f"query-cache stack build failed (serving "
                        f"uncached, will retry): "
                        f"{type(exc).__name__}: {exc}",
                        kind="serving",
                        operator=self.group.label,
                    )
            else:
                self._query_cache_tried = True
        return self._query_cache_stack

    def _cache_refresh_group(self) -> WorkGroup:
        """WorkGroup for deferred stale-entry refreshes: same handler
        surface as the serving group but its batches recompute WITHOUT
        reading the result cache (a read would re-serve the same stale
        entry and never converge)."""
        if self._refresh_group is None:
            from ._utils import estimate_tokens

            self._refresh_group = WorkGroup(
                f"{self.group.label}:cache_refresh",
                self._refresh_batch,
                max_batch=self.group.max_batch,
                token_estimate=lambda payload: estimate_tokens(payload[0]),
            )
        return self._refresh_group

    def _refresh_batch(self, payloads: list[tuple]):
        """Deferred-refresh batch handler (BULK_INGEST class, nobody
        waits on the futures): payloads are ``(query, k, filter, rkey)``.
        Best-effort — a failure or bypass (restoring, breaker open)
        keeps the stale entry in place for its window and is logged,
        never raised into the runtime loop — but the in-flight markers
        are ALWAYS released, so the next stale serve can re-schedule."""
        from ...stdlib.indexing.lowering import live_index_node

        out = [None] * len(payloads)
        stack = self._query_cache_stack
        if stack is None:
            return out
        rkeys = [p[3] for p in payloads]
        try:
            node = live_index_node(self.index_factory)
            if node is None:
                return out
            if getattr(node, "_restore_state", None) == "restoring":
                return out
            if self.breaker is not None and self.breaker.state != "closed":
                return out
            stack.refresh(
                self, node, node.index, [p[:3] for p in payloads], rkeys
            )
        except Exception as exc:  # noqa: BLE001 — best-effort
            from ...internals.errors import register_error

            register_error(
                f"query-cache deferred refresh failed: "
                f"{type(exc).__name__}: {exc}",
                kind="serving",
                operator=self.group.label,
            )
        finally:
            stack.release_refresh(rkeys)
        return out

    def _pack(self, node, row) -> list[dict]:
        from ...internals.value import Json
        from ._utils import coerce_str

        out = []
        for key, score in row:
            payload = node.doc_payload.get(key)
            if payload is None:  # retracted between search and pack
                continue
            meta = payload[self._meta_i]
            if isinstance(meta, Json):
                meta = meta.value
            entry = {
                "text": coerce_str(payload[self._text_i]),
                "metadata": meta,
                "dist": -float(score),
            }
            if self.include_score:
                entry["score"] = float(score)
            out.append(entry)
        return out

    # -- HTTP handler (webserver thread) --
    def aiohttp_handler(self):
        from ._utils import coerce_str, merge_filter_exprs

        async def handle(request):
            from aiohttp import web

            if request.method in ("POST", "PUT", "PATCH"):
                try:
                    payload = await request.json()
                except Exception:  # noqa: BLE001 — malformed body
                    return web.json_response(
                        {"detail": "request body is not valid JSON"}, status=400
                    )
            else:
                payload = dict(request.query)
            query = coerce_str(payload.get("query", ""))
            try:
                k = int(payload.get("k", 3))
            except (TypeError, ValueError):
                return web.json_response({"detail": "invalid k"}, status=400)
            flt = merge_filter_exprs(
                payload.get("metadata_filter"),
                payload.get("filepath_globpattern"),
            )
            deadline_ms = payload.get("deadline_ms", self.deadline_ms)
            try:
                deadline_s = (
                    None if deadline_ms is None else float(deadline_ms) / 1000.0
                )
            except (TypeError, ValueError):
                return web.json_response(
                    {"detail": "invalid deadline_ms"}, status=400
                )
            # trace context minted/adopted by the webserver's tracing
            # middleware: the scheduler stamps queue_wait, the batch
            # handler embed/search — the full per-stage breakdown lands
            # in the flight recorder under this request's trace id
            trace = request.get("pw_trace")
            from ...internals.flight_recorder import trace_stage

            try:
                result = await self.scheduler.submit_async(
                    self.group, (query, k, flt),
                    deadline_s=deadline_s, sheddable=True, trace=trace,
                )
            except DeadlineExceeded as exc:
                shed_body = {"detail": str(exc)}
                if trace is not None:
                    shed_body["trace_id"] = trace.trace_id
                return web.json_response(
                    shed_body,
                    status=503,
                    headers={"Retry-After": f"{exc.retry_after_s:g}"},
                )
            with trace_stage(trace, "serialize"):
                if result["degraded"]:
                    # degraded-mode contract: an object tagging the
                    # fallback, so callers/monitors can tell lexical
                    # answers apart; the healthy path keeps the
                    # plain-list shape for back-compat (the trace id
                    # rides the x-pathway-trace-id header either way)
                    body = {"results": result["results"], "degraded": True}
                    if trace is not None:
                        body["trace_id"] = trace.trace_id
                    resp = web.json_response(body)
                else:
                    resp = web.json_response(result["results"])
            return resp

        return handle
