"""Prompt-template UDFs.

reference: python/pathway/xpacks/llm/prompts.py — ``prompt_qa``:141,
``prompt_qa_geometric_rag``:194, citing QA + cited-response parsing
:268/:316, ``prompt_summarize``:359, query rewrites / HyDE :382/:401,
``RAGPromptTemplate`` protocol :61.
"""

from __future__ import annotations

import re
from typing import Iterable

from ...internals.udfs import udf
from ...internals.value import Json
from ._utils import coerce_str

__all__ = [
    "prompt_qa",
    "prompt_short_qa",
    "prompt_citing_qa",
    "parse_cited_response",
    "prompt_summarize",
    "prompt_query_rewrite",
    "prompt_query_rewrite_hyde",
    "prompt_qa_geometric_rag",
]


def _docs_to_context(docs) -> str:
    if isinstance(docs, Json):
        docs = docs.value
    parts: list[str] = []
    for d in docs or ():
        if isinstance(d, Json):
            d = d.value
        if isinstance(d, dict):
            parts.append(coerce_str(d.get("text", d)))
        else:
            parts.append(coerce_str(d))
    return "\n\n".join(parts)


@udf
def prompt_qa(
    query: str,
    docs,
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
) -> str:
    """reference: prompts.py:141"""
    context = _docs_to_context(docs)
    return (
        "Answer using only the information in the sources below — do not "
        "draw on outside knowledge. Be brief and precise, and begin the "
        "answer with a standalone expression.\n"
        f"If you cannot answer from the sources, say: {information_not_found_response}\n"
        f"{additional_rules}\n"
        f"Sources:\n{context}\n"
        f"Question: {query}\n"
        "Answer:"
    )


@udf
def prompt_short_qa(
    query: str,
    docs,
    additional_rules: str = "",
) -> str:
    """Few-word answer variant (reference: prompts.py short-qa template)."""
    context = _docs_to_context(docs)
    return (
        "Answer in just a few words, using only the information in the "
        "sources below.\n"
        f"{additional_rules}\n"
        f"Sources:\n{context}\n"
        f"Question: {query}\n"
        "Answer:"
    )


def prompt_qa_geometric_rag(
    query: str,
    docs: Iterable,
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
    strict_prompt: bool = False,
) -> str:
    """Plain function used inside the adaptive-RAG loop
    (reference: prompts.py:194; called from
    question_answering.answer_with_geometric_rag_strategy)."""
    docs_str = "\n".join(
        f"Source {i + 1}: {coerce_str(d)}" for i, d in enumerate(docs)
    )
    if strict_prompt:
        rule = (
            "Only answer with a short phrase taken from the sources, or "
            f'exactly "{information_not_found_response}".'
        )
    else:
        rule = f"If you cannot answer, reply: {information_not_found_response}"
    return (
        "Use the below articles to answer the subsequent question. "
        f"{rule}\n{additional_rules}\n"
        f"{docs_str}\n"
        f"Question: {query}\n"
        "Answer:"
    )


@udf
def prompt_citing_qa(
    query: str,
    docs,
    additional_rules: str = "",
) -> str:
    """reference: prompts.py:268"""
    context = _docs_to_context(docs)
    return (
        "Answer using only the information in the sources below — do not "
        "draw on outside knowledge. When a statement comes from a source, "
        "cite that source by its number like [1], [2]; every answer must "
        "carry at least one citation.\n"
        f"{additional_rules}\n"
        f"Sources:\n{context}\n"
        f"Question: {query}\n"
        "Answer:"
    )


@udf
def parse_cited_response(response: str, docs) -> Json:
    """Split a cited answer into (answer, cited source indices)
    (reference: prompts.py:316)."""
    text = coerce_str(response)
    cited = sorted({int(m) - 1 for m in re.findall(r"\[(\d+)\]", text)})
    if isinstance(docs, Json):
        docs = docs.value
    docs = list(docs or ())
    cited_docs = [
        (d.value if isinstance(d, Json) else d)
        for i, d in enumerate(docs)
        if i in cited
    ]
    return Json(
        {
            "response": re.sub(r"\s*\[\d+\]", "", text).strip(),
            "citations": cited,
            "cited_docs": cited_docs,
        }
    )


@udf
def prompt_summarize(text_list) -> str:
    """reference: prompts.py:359"""
    if isinstance(text_list, Json):
        text_list = text_list.value
    text = "\n".join(coerce_str(t) for t in (text_list or ()))
    return (
        "Summarize the given texts, make sure the summary covers all the "
        "texts:\n"
        f"{text}\n"
        "Summary:"
    )


@udf
def prompt_query_rewrite(query: str, additional_rules: str = "") -> str:
    """reference: prompts.py:382"""
    return (
        "Rewrite the following search query to be cleaner and more likely "
        "to match relevant documents. Keep all the named entities.\n"
        f"{additional_rules}\n"
        f"Query: {coerce_str(query)}\n"
        "Rewritten query:"
    )


@udf
def prompt_query_rewrite_hyde(query: str) -> str:
    """reference: prompts.py:401 (HyDE)"""
    return (
        "Write a short passage that plausibly answers the question below — "
        "it will be used to search for relevant documents.\n"
        f"Question: {coerce_str(query)}\n"
        "Passage:"
    )
