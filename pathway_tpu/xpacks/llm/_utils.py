"""Shared helpers for the LLM xpack.

reference: python/pathway/xpacks/llm/_utils.py (coerce helpers) — the
``_AsyncMicroBatcher`` is new here: it is the device-batching half of the
TPU design.  The reference embeds one string per async-UDF call and gets
concurrency from the executor only (embedders.py async UDF w/ capacity);
here all calls that are in flight on the same event loop coalesce into one
padded device batch, so a micro-batch of N chunks costs one jit dispatch
instead of N model calls.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Sequence

# ONE token-estimate implementation for every budget-batching plane —
# it lives in the runtime package now (the unified executor composes
# ticks from the same estimates); re-exported here for back-compat
from ...runtime import estimate_tokens

__all__ = [
    "coerce_str",
    "estimate_tokens",
    "AsyncMicroBatcher",
    "RestClientBase",
    "run_with_cache",
    "merge_filter_exprs",
    "_check_model_accepts_arg",
]


def coerce_str(value: Any) -> str:
    if isinstance(value, bytes):
        return value.decode("utf-8", errors="replace")
    return str(value)


def seed_embedder_mesh(embedder: Any, mesh: Any) -> None:
    """Thread a serving mesh into a model-backed embedder whose encoder
    is not built yet (``_encoder is None`` + ``_init_kwargs``): query and
    ingest encodes then run data-parallel over the same device set the
    index shards on.  Already-built encoders and plain UDF embedders are
    left alone.  Shared by ``VectorStoreServer`` and ``DocumentStore``
    so the ``mesh=``/``PATHWAY_SERVING_MESH`` knob behaves identically
    through both entry points."""
    if (
        mesh is not None
        and embedder is not None
        and getattr(embedder, "_encoder", "-") is None
        and hasattr(embedder, "_init_kwargs")
    ):
        existing = embedder._init_kwargs.get("mesh")
        if existing is None:
            embedder._init_kwargs["mesh"] = mesh
        elif existing is not mesh:
            # one embedder reused across servers with DIFFERENT meshes
            # keeps the first mesh it bound — its encoder is (or will
            # be) committed to those devices, and silently rebinding
            # would feed one server queries placed on the other's mesh.
            # Loud, because the fused tick will degrade on the mismatch.
            import warnings

            warnings.warn(
                "embedder already bound to a different serving mesh; "
                "reusing one embedder across servers with different "
                "meshes keeps the first — pass a fresh embedder per mesh",
                stacklevel=3,
            )


def merge_filter_exprs(
    metadata_filter: str | None, filepath_globpattern: str | None
) -> str | None:
    """Combine the two request filters into one expression
    (reference: vector_store.py:358 ``merge_filters``) — plain-function
    form shared by the dataflow UDF and the scheduler retrieve plane."""
    parts = []
    if metadata_filter:
        parts.append(f"({metadata_filter})")
    if filepath_globpattern:
        parts.append(f"globmatch('{filepath_globpattern}', path)")
    return " && ".join(parts) if parts else None


def _check_model_accepts_arg(model_cls_or_fn: Any, arg: str) -> bool:
    import inspect

    try:
        sig = inspect.signature(model_cls_or_fn)
    except (TypeError, ValueError):
        return False
    return arg in sig.parameters


class RestClientBase:
    """Shared urllib JSON client (VectorStoreClient / RAGClient).

    ``retry_on_unavailable`` (off by default) makes a 503 response —
    the serving scheduler's deadline/overload shedding — degrade
    gracefully: the client retries with jittered exponential backoff
    (``backoff_initial_s`` · ``backoff_factor``^attempt, up to
    ``max_retries`` attempts), honoring the server's ``Retry-After``
    hint when present.  Every individual sleep is clamped to
    ``max_retry_after_s`` and the whole retry budget to
    ``retry_deadline_s`` of wall clock — a saturated server makes the
    client fail fast after the deadline instead of piling on.

    Every response's ``x-pathway-trace-id`` header is captured as
    ``last_trace_id`` — paste it into the server's
    ``/v1/debug/traces?trace_id=...`` to see where that exact request's
    time went (queue wait / embed / search / serialize).

    Every logical call mints ONE W3C ``traceparent`` and reuses it
    across its 503 retries: the retried attempts stitch into a single
    trace on the server instead of minting a fresh id per attempt — a
    retried request used to be invisible as such in the trace dump,
    which hid exactly the client-side pile-on behavior the retry knobs
    bound.
    """

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: float = 30.0,
        additional_headers: dict | None = None,
        retry_on_unavailable: bool = False,
        max_retry_after_s: float = 5.0,
        max_retries: int = 4,
        backoff_initial_s: float = 0.25,
        backoff_factor: float = 2.0,
        backoff_jitter_s: float = 0.1,
        retry_deadline_s: float = 10.0,
    ):
        if url is None:
            if host is None or port is None:
                raise ValueError("provide url= or host= and port=")
            url = f"http://{host}:{port}"
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.additional_headers = additional_headers or {}
        self.retry_on_unavailable = retry_on_unavailable
        self.max_retry_after_s = max_retry_after_s
        self.max_retries = max_retries
        self.backoff_initial_s = backoff_initial_s
        self.backoff_factor = backoff_factor
        self.backoff_jitter_s = backoff_jitter_s
        self.retry_deadline_s = retry_deadline_s
        #: trace id of the most recent response (server-minted, or the
        #: caller's own traceparent's trace id when one was sent)
        self.last_trace_id: str | None = None

    def _new_traceparent(self) -> str:
        """One trace context per LOGICAL call (shared by every retry of
        it; adaptive re-ask rounds that reuse one client call stitch in
        too)."""
        from ...internals.flight_recorder import (
            format_traceparent,
            new_span_id,
            new_trace_id,
        )

        return format_traceparent(new_trace_id(), new_span_id())

    def _post(self, route: str, payload: dict):
        import random
        import time
        import urllib.error

        deadline = time.monotonic() + self.retry_deadline_s
        attempt = 0
        traceparent = self._new_traceparent()
        while True:
            try:
                return self._post_once(route, payload, traceparent=traceparent)
            except urllib.error.HTTPError as exc:
                if not (self.retry_on_unavailable and exc.code == 503):
                    raise
                if attempt >= self.max_retries:
                    raise
                retry_after = None
                try:
                    header = exc.headers.get("Retry-After")
                    if header is not None:
                        retry_after = float(header)
                except (TypeError, ValueError):
                    retry_after = None
                delay = (
                    retry_after
                    if retry_after is not None
                    else self.backoff_initial_s
                    * (self.backoff_factor ** attempt)
                )
                # jitter scales with the delay (≥ the configured floor):
                # a draining/overloaded replica hands every client the
                # SAME Retry-After, and a fixed sleep would march them
                # all back in lockstep — proportional jitter decorrelates
                # the herd
                delay += random.uniform(
                    0.0, max(self.backoff_jitter_s, 0.25 * delay)
                )
                delay = max(0.0, min(delay, self.max_retry_after_s))
                if time.monotonic() + delay > deadline:
                    # total-deadline cap: fail fast instead of sleeping
                    # past the caller's patience
                    raise
                time.sleep(delay)
                attempt += 1

    def _post_once(
        self, route: str, payload: dict, traceparent: str | None = None
    ):
        import json
        import urllib.request

        headers = {"Content-Type": "application/json", **self.additional_headers}
        if traceparent is not None and "traceparent" not in {
            k.lower() for k in headers
        }:
            headers["traceparent"] = traceparent
        req = urllib.request.Request(
            self.url + route,
            data=json.dumps(payload).encode(),
            headers=headers,
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            trace_id = resp.headers.get("x-pathway-trace-id")
            if trace_id is not None:
                self.last_trace_id = trace_id
            return json.loads(resp.read().decode())


def run_with_cache(
    threaded: bool = False,
    with_cache: bool = True,
    cache_backend: Any = None,
    terminate_on_error: bool = True,
    persistence_config: Any = None,
):
    """Start ``pw.run`` with UDF_CACHING persistence wired (reference:
    vector_store.py:558-582 / servers.py run) — shared by every xpack
    ``run_server``.  Returns the thread when ``threaded=True``.

    An explicit ``persistence_config`` (durable serving: the recovery
    plane under ``PersistenceMode.OPERATOR_PERSISTING``) takes precedence
    over the default in-memory UDF cache."""
    from ...internals.run import run

    if persistence_config is None and with_cache:
        from ...persistence import Backend, Config

        backend = cache_backend or Backend.mock()
        persistence_config = Config(backend, persistence_mode="UDF_CACHING")

    def target():
        run(
            persistence_config=persistence_config,
            terminate_on_error=terminate_on_error,
        )

    if threaded:
        th = threading.Thread(target=target, daemon=True, name="pw-server")
        th.start()
        return th
    target()


class AsyncMicroBatcher:
    """Coalesces concurrent async calls into one batched device call.

    ``batch_fn(list_of_items) -> list_of_results`` is invoked once per
    scheduling round of the event loop (or when ``max_batch`` items are
    pending).  The engine's AsyncMapNode fans out every row of a micro-batch
    as a concurrent task on one loop, so all rows of the timestamp land in
    the same device batch — the bucketed-padding path of
    ``models/encoder.py`` then compiles once per shape bucket.

    When shared-executor serving is enabled (the default) calls delegate
    to the process-wide executor instead: work coalesces ACROSS engine
    steps and REST planes, not just within one loop round, and every
    device dispatch serializes on the executor thread.  Under the
    unified device-tick runtime (``PATHWAY_RUNTIME=1``, default) the
    batcher submits its items as ``LLM_RERANK``-class work — below
    interactive serving ticks, above bulk ingest; with
    ``PATHWAY_RUNTIME=0`` it delegates to the legacy
    :class:`~pathway_tpu.xpacks.llm._scheduler.ServingScheduler` loop.
    ``use_scheduler`` pins the behavior per batcher (None = follow the
    global ``PATHWAY_SERVING_SCHEDULER`` setting; False = per-loop
    micro-batching only).
    """

    def __init__(
        self,
        batch_fn: Callable[[list], Sequence],
        max_batch: int = 1024,
        use_scheduler: bool | None = None,
        max_tokens: int | None = None,
        token_estimate: Callable[[Any], int] | None = None,
    ):
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        # token-budget admission: a flush fires once the PENDING batch's
        # estimated token mass reaches ``max_tokens`` — batch size adapts
        # to document length, so a run of long documents flushes small
        # while a run of tweets still fills ``max_batch``.  The serving
        # scheduler honors the same attributes when it chunk-drains this
        # batcher as a WorkGroup.
        self.max_tokens = max_tokens
        self.token_estimate = token_estimate or estimate_tokens
        self.label = getattr(batch_fn, "__name__", "batch")
        self.use_scheduler = use_scheduler
        # device dispatch is serialized; the model call itself is not
        # thread-safe across loops
        self._dispatch_lock = threading.Lock()
        self._pending: dict[int, list[tuple[Any, asyncio.Future]]] = {}
        self._pending_tokens: dict[int, int] = {}

    def _scheduler(self):
        from ._scheduler import get_scheduler, scheduler_enabled

        use = self.use_scheduler
        if use is None:
            use = scheduler_enabled()
        return get_scheduler() if use else None

    async def call(self, item: Any) -> Any:
        use = self.use_scheduler
        if use is None:
            from ._scheduler import scheduler_enabled

            use = scheduler_enabled()
        if use:
            from ...runtime import QoS, get_runtime, runtime_enabled

            if runtime_enabled():
                # engine-plane embed/rerank/LLM-guard work rides the
                # unified runtime as LLM_RERANK: below interactive
                # serving, above bulk ingest, never shed (no deadline)
                return await get_runtime().submit_async(
                    self, item, qos=QoS.LLM_RERANK
                )
        sched = self._scheduler() if use else None
        if sched is not None:
            # engine-plane work carries no deadline: it is never shed
            return await sched.submit_async(self, item)
        loop = asyncio.get_running_loop()
        lid = id(loop)
        lst = self._pending.setdefault(lid, [])
        fut: asyncio.Future = loop.create_future()
        lst.append((item, fut))
        over_tokens = False
        if self.max_tokens is not None:
            tokens = self._pending_tokens.get(lid, 0) + self.token_estimate(item)
            self._pending_tokens[lid] = tokens
            over_tokens = tokens >= self.max_tokens
        if len(lst) >= self.max_batch or over_tokens:
            self._flush(lid)
        elif len(lst) == 1:
            # flush after the current scheduling round: every concurrent
            # task gets to append before the callback runs
            loop.call_soon(self._flush, lid)
        return await fut

    def _flush(self, lid: int) -> None:
        lst = self._pending.get(lid)
        if not lst:
            return
        self._pending[lid] = []
        self._pending_tokens[lid] = 0
        items = [it for it, _ in lst]
        try:
            with self._dispatch_lock:
                results = self.batch_fn(items)
            for (_, fut), res in zip(lst, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as exc:  # noqa: BLE001 — propagate to every waiter
            for _, fut in lst:
                if not fut.done():
                    fut.set_exception(exc)


#: static per-provider parameter tables — the reference resolves these
#: through litellm.get_supported_openai_params (llms.py _utils); when
#: litellm is importable we do the same, else these serve as the offline
#: fallback so _accepts_call_arg stays accurate without the dependency
_PROVIDER_PARAMS = {
    "openai": {
        "model", "temperature", "max_tokens", "max_completion_tokens",
        "top_p", "n", "stop", "seed", "presence_penalty",
        "frequency_penalty", "logit_bias", "logprobs", "top_logprobs",
        "response_format", "tools", "tool_choice", "user", "stream",
    },
    "cohere": {
        "model", "temperature", "max_tokens", "p", "k", "seed",
        "stop_sequences", "frequency_penalty", "presence_penalty",
        "documents",
    },
}


def check_provider_accepts_arg(model: str, provider: str, arg: str) -> bool:
    """reference: xpacks/llm/_utils.py ``_check_model_accepts_arg`` —
    ask litellm for the model's supported OpenAI-style params, falling
    back to a static provider table offline."""
    try:
        import litellm

        params = litellm.get_supported_openai_params(
            model=model, custom_llm_provider=provider
        )
        if params:
            return arg in params
    except Exception:
        pass
    return arg in _PROVIDER_PARAMS.get(provider, set())


def prep_message_log(messages: list, verbose: bool) -> str:
    """Shorten chat messages for structured request logs (reference:
    llms.py:55 ``_prep_message_log``): verbose mode redacts inline
    images, non-verbose truncates."""
    import copy
    import json as _json

    if verbose:
        log_messages = copy.deepcopy(messages)
        for message in log_messages:
            content = message.get("content")
            if isinstance(content, list):
                for part in content:
                    if isinstance(part, dict) and part.get("type") == "image_url":
                        part["image_url"] = {"url": "<redacted image>"}
        return _json.dumps(log_messages, ensure_ascii=False, default=str)
    text = _json.dumps(messages, ensure_ascii=False, default=str)
    return text[:500] + ("..." if len(text) > 500 else "")
