"""Embedder UDFs.

reference: python/pathway/xpacks/llm/embedders.py — ``BaseEmbedder``:64
(with ``get_embedding_dimension``:72), ``OpenAIEmbedder``:85,
``LiteLLMEmbedder``:180, ``SentenceTransformerEmbedder``:270,
``GeminiEmbedder``:330.

TPU design: ``SentenceTransformerEmbedder`` runs the MiniLM-class flax
encoder (models/encoder.py) jit-compiled on the TPU.  Calls arriving
concurrently within one engine micro-batch coalesce into a single padded
device batch via :class:`AsyncMicroBatcher` — the reference's per-string
torch calls become one MXU matmul chain per timestamp.  API embedders
(OpenAI/LiteLLM/Gemini) keep the reference's async-UDF shape (capacity,
retries, cache) and need the respective client libraries at call time.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ...internals import udfs
from ...internals.udfs import UDF
from ._utils import AsyncMicroBatcher, coerce_str

__all__ = [
    "BaseEmbedder",
    "SentenceTransformerEmbedder",
    "ImageEmbedder",
    "OpenAIEmbedder",
    "LiteLLMEmbedder",
    "GeminiEmbedder",
]


class BaseEmbedder(UDF):
    """reference: embedders.py:64"""

    def get_embedding_dimension(self, **kwargs) -> int:
        """Dimension learned by probing with ".", like the reference
        (embedders.py:72 / nearest_neighbors.py:411)."""
        return len(_call_sync(self.__wrapped__, ".", **kwargs))


def _call_sync(fn: Callable, *args, **kwargs):
    import asyncio
    import inspect

    if inspect.iscoroutinefunction(fn):
        return asyncio.run(fn(*args, **kwargs))
    res = fn(*args, **kwargs)
    if inspect.iscoroutine(res):
        return asyncio.run(res)
    return res


class SentenceTransformerEmbedder(BaseEmbedder):
    """JAX/flax sentence encoder on TPU
    (reference: embedders.py:270 — sentence-transformers torch model with a
    ``device`` param; here device placement is XLA's and the model is the
    bucketed-batch jit encoder of models/encoder.py).

    ``model`` accepts an all-MiniLM-L6-v2-style name (geometry + wordpiece
    vocab are resolved by models/tokenizer.py), or pass ``encoder=`` with a
    ready :class:`pathway_tpu.models.encoder.SentenceEncoder`.
    """

    def __init__(
        self,
        model: str = "all-MiniLM-L6-v2",
        *,
        call_kwargs: dict = {},
        device: str = "tpu",  # accepted for API parity; placement is XLA's
        encoder: Any = None,
        max_batch: int = 1024,
        max_tokens: int | None = None,
        pipelined: bool = False,
        use_scheduler: bool | None = None,
        **init_kwargs,
    ):
        # pipelined: fully-async dispatch — the device encode of micro-batch
        # t overlaps host ingest/parse of t+1, embeddings land one engine
        # step later (the FullyAsyncExecutor contract)
        # use_scheduler: None follows the global serving-scheduler setting
        # (calls coalesce across engine steps and REST planes); False pins
        # the per-loop micro-batching
        # max_tokens: token-budget admission (None = PATHWAY_EMBED_MAX_TOKENS)
        # — batch size adapts to document length instead of a bare count cap
        super().__init__(
            executor=(
                udfs.fully_async_executor() if pipelined else udfs.async_executor()
            ),
            deterministic=True,
        )
        self.model = model
        self.kwargs = dict(call_kwargs)
        self._encoder = encoder
        self._batcher: AsyncMicroBatcher | None = None
        self._max_batch = max_batch
        if max_tokens is None:
            from ...models.encoder import embed_max_tokens

            max_tokens = embed_max_tokens()
        self._max_tokens = max_tokens
        self._use_scheduler = use_scheduler
        self._init_kwargs = init_kwargs

    def _ensure_encoder(self):
        if self._encoder is None:
            from ...models.encoder import SentenceEncoder

            self._encoder = SentenceEncoder(self.model, **self._init_kwargs)
        if self._batcher is None:
            enc = self._encoder

            def batch_encode(texts: list[str]) -> list[np.ndarray]:
                return list(enc.encode([coerce_str(t) for t in texts]))

            self._batcher = AsyncMicroBatcher(
                batch_encode, max_batch=self._max_batch,
                use_scheduler=self._use_scheduler,
                max_tokens=self._max_tokens,
            )
        return self._encoder

    async def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        self._ensure_encoder()
        return await self._batcher.call(input)

    def get_embedding_dimension(self, **kwargs) -> int:
        return self._ensure_encoder().dim


class ImageEmbedder(BaseEmbedder):
    """JAX vision-transformer image embedder for multimodal RAG
    (BASELINE config #5: CLIP image + text embedders over a hybrid index;
    models/vision.py).  Takes image bytes (or arrays); concurrent calls
    batch into one padded device dispatch like the text embedder."""

    def __init__(
        self,
        *,
        encoder: Any = None,
        max_batch: int = 256,
        use_scheduler: bool | None = None,
        **init_kwargs,
    ):
        super().__init__(executor=udfs.async_executor(), deterministic=True)
        self._encoder = encoder
        self._batcher: AsyncMicroBatcher | None = None
        self._max_batch = max_batch
        self._use_scheduler = use_scheduler
        self._init_kwargs = init_kwargs

    def _ensure_encoder(self):
        if self._encoder is None:
            from ...models.vision import ImageEncoder as _ImageEncoder

            self._encoder = _ImageEncoder(**self._init_kwargs)
        if self._batcher is None:
            enc = self._encoder

            def batch_encode(images: list) -> list[np.ndarray]:
                return list(enc.encode(images))

            self._batcher = AsyncMicroBatcher(
                batch_encode, max_batch=self._max_batch,
                use_scheduler=self._use_scheduler,
            )
        return self._encoder

    async def __wrapped__(self, input, **kwargs) -> np.ndarray:
        self._ensure_encoder()
        return await self._batcher.call(input)

    def get_embedding_dimension(self, **kwargs) -> int:
        return self._ensure_encoder().dim


class OpenAIEmbedder(BaseEmbedder):
    """reference: embedders.py:85 — async UDF calling the OpenAI embeddings
    API; capacity/retry/cache strategies as in the reference."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "text-embedding-3-small",
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **openai_kwargs,
    ):
        super().__init__(
            executor=udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(openai_kwargs)
        if model is not None:
            self.kwargs["model"] = model
        self._client = None

    def _ensure_client(self):
        if self._client is None:
            import openai  # noqa: F401 — optional dependency

            self._client = openai.AsyncOpenAI(
                **{
                    k: self.kwargs.pop(k)
                    for k in ("api_key", "base_url", "organization")
                    if k in self.kwargs
                }
            )
        return self._client

    async def __wrapped__(self, input, **kwargs) -> np.ndarray:
        client = self._ensure_client()
        kwargs = {**self.kwargs, **kwargs}
        input = coerce_str(input) or "."
        ret = await client.embeddings.create(input=[input], **kwargs)
        return np.array(ret.data[0].embedding)


class LiteLLMEmbedder(BaseEmbedder):
    """reference: embedders.py:180"""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **llmlite_kwargs,
    ):
        super().__init__(
            executor=udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.kwargs = dict(llmlite_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    async def __wrapped__(self, input, **kwargs) -> np.ndarray:
        import litellm  # optional dependency

        ret = await litellm.aembedding(
            input=[coerce_str(input) or "."], **{**self.kwargs, **kwargs}
        )
        return np.array(ret.data[0]["embedding"])


class GeminiEmbedder(BaseEmbedder):
    """reference: embedders.py:330"""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "models/text-embedding-004",
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **genai_kwargs,
    ):
        super().__init__(
            executor=udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.kwargs = dict(genai_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    async def __wrapped__(self, input, **kwargs) -> np.ndarray:
        import google.generativeai as genai  # optional dependency

        ret = genai.embed_content(content=coerce_str(input) or ".", **{**self.kwargs, **kwargs})
        return np.array(ret["embedding"])
