"""Deterministic fakes for xpack tests — no model, no network.

reference: python/pathway/xpacks/llm/tests/mocks.py
(``fake_embeddings_model``:5, ``IdentityMockChat``:16) plus the
``FakeChatModel`` used across xpack tests.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ...internals import udfs
from ...internals.udfs import UDF, udf
from ...internals.value import Json
from ._utils import coerce_str
from .embedders import BaseEmbedder
from .llms import BaseChat

__all__ = [
    "fake_embeddings_model",
    "FakeEmbedder",
    "IdentityMockChat",
    "FakeChatModel",
]


def _fake_embedding(text: str, dim: int = 3) -> np.ndarray:
    """Deterministic pseudo-embedding: hash-seeded unit vector.  Identical
    strings map to identical vectors, so exact-match retrieval is testable."""
    h = hashlib.blake2b(coerce_str(text).encode(), digest_size=8).digest()
    rng = np.random.default_rng(int.from_bytes(h, "little"))
    v = rng.standard_normal(dim).astype(np.float32)
    return v / np.linalg.norm(v)


@udf
def fake_embeddings_model(x: str) -> np.ndarray:
    """reference: tests/mocks.py:5"""
    return _fake_embedding(x)


class FakeEmbedder(BaseEmbedder):
    """Class-form fake with a configurable dimension."""

    def __init__(self, dim: int = 8):
        super().__init__(deterministic=True)
        self.dim = dim

    def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        return _fake_embedding(input, self.dim)

    def get_embedding_dimension(self, **kwargs) -> int:
        return self.dim


class IdentityMockChat(BaseChat):
    """Echoes "model::last user message" (reference: tests/mocks.py:16)."""

    def __init__(self, model: str = "mock"):
        super().__init__(deterministic=True)
        self.model = model

    def __wrapped__(self, messages, model: str | None = None, **kwargs) -> str:
        from .llms import _messages_to_list

        msgs = _messages_to_list(messages)
        return f"{model or self.model}::{msgs[-1]['content']}"


class FakeChatModel(BaseChat):
    """Returns a canned answer regardless of the prompt."""

    def __init__(self, response: str = "Text"):
        super().__init__(deterministic=True)
        self.response = response

    def __wrapped__(self, messages, **kwargs) -> str:
        return self.response
