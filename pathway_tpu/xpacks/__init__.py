"""``pw.xpacks`` — extension packs.

reference: python/pathway/xpacks/ (llm xpack + gated connectors).
"""

from . import connectors, llm

__all__ = ["connectors", "llm"]
