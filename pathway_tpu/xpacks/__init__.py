"""``pw.xpacks`` — extension packs.

reference: python/pathway/xpacks/ (llm xpack + gated connectors).
"""

from . import llm

__all__ = ["llm"]
