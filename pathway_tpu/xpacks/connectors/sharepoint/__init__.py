"""``pw.xpacks.connectors.sharepoint`` — SharePoint document source.

reference: python/pathway/xpacks/connectors/sharepoint (376 LoC) — polls a
SharePoint document library via Office365-REST-Python-Client, emitting
file contents + metadata with modification/deletion diffs (same shape as
pw.io.gdrive).  Needs ``office365`` at call time.
"""

from __future__ import annotations

import time as _time
from typing import Any

from ....internals.keys import ref_scalar
from ....internals.schema import schema_from_types
from ....internals.table import Table
from ....internals.value import Json
from ....io._utils import input_table, with_metadata_schema
from ....io.streaming import ConnectorSubject

__all__ = ["read"]


class _SharePointSubject(ConnectorSubject):
    _shared_source = True

    def __init__(self, context, root_path, mode, refresh_s, with_metadata, autocommit_ms):
        super().__init__(datasource_name=f"sharepoint:{root_path}")
        self.context = context
        self.root_path = root_path
        self._mode = "static" if mode == "static" else "streaming"
        self.refresh_s = refresh_s
        self.with_metadata = with_metadata
        self._autocommit_ms = autocommit_ms
        self._seen: dict[str, tuple] = {}

    def _scan(self) -> None:
        folder = self.context.web.get_folder_by_server_relative_url(self.root_path)
        files = folder.files.get().execute_query()
        current = {}
        for f in files:
            current[f.serverRelativeUrl] = str(f.time_last_modified)
        for url in list(self._seen):
            if url not in current:
                _, key, values = self._seen.pop(url)
                self._remove(key, values)
        for url, stamp in current.items():
            old = self._seen.get(url)
            if old is not None and old[0] == stamp:
                continue
            if old is not None:
                self._remove(old[1], old[2])
            import io as _io

            buf = _io.BytesIO()
            self.context.web.get_file_by_server_relative_url(url).download(
                buf
            ).execute_query()
            key = ref_scalar("__sharepoint__", url)
            row = {"data": buf.getvalue()}
            if self.with_metadata:
                row["_metadata"] = Json({"path": url, "modified_at": stamp})
            values = tuple(row.get(n) for n in self._column_names)
            self._add_inner(key, values)
            self._seen[url] = (stamp, key, values)
        self.commit()

    def run(self) -> None:
        self._scan()
        if self._mode == "static":
            return
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            self._scan()

    def current_offsets(self):
        return dict(self._seen)

    def seek(self, offsets) -> None:
        if offsets:
            self._seen = dict(offsets)


def read(
    url: str,
    *,
    tenant: str | None = None,
    client_id: str | None = None,
    cert_path: str | None = None,
    thumbprint: str | None = None,
    root_path: str = "",
    context: Any = None,
    mode: str = "streaming",
    refresh_interval: float = 30.0,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if context is None:
        from office365.sharepoint.client_context import ClientContext  # optional dependency

        context = ClientContext(url).with_client_certificate(
            tenant, client_id, thumbprint, cert_path
        )
    schema = schema_from_types(data=bytes)
    out_schema = with_metadata_schema(schema) if with_metadata else schema
    subject = _SharePointSubject(
        context, root_path, mode, refresh_interval, with_metadata,
        autocommit_duration_ms,
    )
    subject.persistent_id = persistent_id
    subject._configure(out_schema, None)
    return input_table(out_schema, subject=subject)
