"""``pw.xpacks.connectors`` — gated service connectors
(reference: python/pathway/xpacks/connectors/)."""

from . import sharepoint

__all__ = ["sharepoint"]
