"""``pw.io.logstash`` — Logstash HTTP-input sink
(reference: python/pathway/io/logstash — a thin wrapper over the HTTP
sink pointed at logstash's http input plugin)."""

from __future__ import annotations

from ...internals.table import Table
from ..http._client import write as _http_write

__all__ = ["write"]


def write(table: Table, endpoint: str, n_retries: int = 0, **kwargs) -> None:
    _http_write(table, endpoint, **kwargs)
