"""``pw.io.csv`` — CSV read/write.

reference: python/pathway/io/csv/__init__.py (read, write) over the Rust
dsv format (src/connectors/data_format.rs) and FileWriter
(data_storage.rs:649).
"""

from __future__ import annotations

import csv as _csv
from pathlib import Path
from typing import Any

from ...internals.schema import SchemaMetaclass
from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["read", "write"]


def read(
    path: str | Path,
    *,
    schema: SchemaMetaclass,
    mode: str = "streaming",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    parser_settings=None,
    **kwargs: Any,
) -> Table:
    from .. import fs

    return fs.read(
        path,
        format="csv",
        schema=schema,
        csv_settings=parser_settings,
        mode=mode,
        with_metadata=with_metadata,
        autocommit_duration_ms=autocommit_duration_ms,
        **kwargs,
    )


def write(table: Table, filename: str | Path) -> None:
    """Append the update stream as CSV rows + ``time``/``diff`` columns
    (reference dsv formatter writes the same trailer columns)."""
    names = table.column_names()
    f = open(filename, "w", newline="")
    writer = _csv.writer(f)
    writer.writerow(names + ["time", "diff"])

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        writer.writerow([row[n] for n in names] + [time, 1 if is_addition else -1])
        f.flush()

    subscribe(table, on_change=on_change, on_end=f.close, name=f"csv:{filename}")
