"""Connector subjects + the streaming run loop.

reference: src/connectors/mod.rs (``Connector::run`` reader thread :427,
commit ticks every ``commit_duration`` :207-217, ``SessionType`` adaptors)
and python/pathway/io/python/__init__.py:49 (``ConnectorSubject``).

TPU-era shape: connectors stay host-side threads exactly like the
reference's reader threads, but instead of feeding timely input sessions
over crossbeam channels they buffer diffs that the ``StreamingDriver``
stamps with a micro-batch timestamp and pushes through the engine — one
``engine.step(t)`` per commit is the analogue of a timely epoch.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time as _time
from typing import Any, Callable, Iterable

from ..internals.engine import Engine, Entry, SourceNode
from ..internals.keys import ref_scalar
from ..internals.value import Json, Pointer
from ..testing import faults

__all__ = [
    "ConnectorSubject",
    "ConnectorSupervisor",
    "StreamingDriver",
    "next_autogen_key",
]

logger = logging.getLogger(__name__)

_autogen_lock = threading.Lock()
_autogen_counter = 0


def next_autogen_key(salt: Any = "io") -> Pointer:
    global _autogen_counter
    with _autogen_lock:
        _autogen_counter += 1
        return ref_scalar("__io_autogen__", salt, _autogen_counter)


class ConnectorSubject:
    """Base class for custom Python input connectors.

    Subclass and implement :meth:`run`, emitting rows via :meth:`next` /
    :meth:`next_json` / :meth:`next_str` / :meth:`next_bytes`; call
    :meth:`commit` to make emitted rows visible atomically and
    :meth:`close` when the stream ends (reference
    io/python/__init__.py:49-214).
    """

    #: "streaming" subjects run on their own thread under pw.run;
    #: "static" subjects are drained synchronously at build time so batch
    #: graphs (pw.debug helpers) see their data without a driver.
    _mode: str = "streaming"
    #: "native" = emitted diffs pass through; "upsert" = a second row with
    #: the same key replaces the first (reference SessionType::Upsert)
    _session_type: str = "native"
    #: commit pending rows automatically every N ms even without an
    #: explicit commit() (reference: connector commit_duration ticks,
    #: src/connectors/mod.rs:207-217); None = explicit commits only
    _autocommit_ms: int | None = None
    #: key under which this subject's input snapshot + offsets persist
    #: (reference: persistent_id on connectors).  Snapshotting is opt-in:
    #: subjects that neither set an explicit persistent_id nor override
    #: current_offsets()/seek() are not persisted (replaying them would
    #: double records).  The default key for offset-tracking subjects is
    #: "{datasource_name}-{occurrence}" (occurrence among same-named
    #: sources in graph order), process-scoped in multi-process runs.
    persistent_id: str | None = None
    #: True for sources every process can see identically (fs/s3/sqlite
    #: scanners): in multi-process runs each process keeps only the keys it
    #: owns, so a record enters the system exactly once globally.  False
    #: for process-local subjects (REST requests, custom python sources).
    _shared_source: bool = False
    #: supervision (ConnectorSupervisor): a reader exception no longer
    #: silently kills the source — run() is restarted with exponential
    #: backoff up to ``_max_restarts`` times (None = env
    #: PATHWAY_CONNECTOR_MAX_RESTARTS, default 3), then the connector is
    #: marked failed on /v1/health while the run keeps going.  Set
    #: ``_supervised = False`` for subjects whose run() is not safely
    #: re-enterable (emits non-idempotent rows without dedup/upsert).
    _supervised: bool = True
    _max_restarts: int | None = None
    #: request-scoped sources (REST handlers) whose rows are in-flight
    #: client requests: nothing to restore on restart (clients retry), so
    #: OPERATOR_PERSISTING's seekability coverage check exempts them
    _ephemeral: bool = False
    #: fault-injection site for rows this subject pushes (None = exempt,
    #: e.g. the error-log subjects themselves)
    _fault_site: str | None = "connector.read"
    #: "raise" (default) re-raises malformed payloads into the reader
    #: (supervisor territory); "dead_letter" routes them to the global
    #: error log + dead-letter sinks and keeps consuming
    _on_error: str = "raise"

    def __init__(self, datasource_name: str = "python") -> None:
        self._datasource_name = datasource_name
        self._lock = threading.Lock()
        self._pending: list[tuple[str, Any, tuple | None]] = []  # op, key, values
        self._committed: list[list[tuple[str, Any, tuple | None]]] = []
        self._closed = threading.Event()
        self._started = False
        self._schema = None
        self._column_names: list[str] = []
        self._primary_key: list[str] | None = None
        self._last_by_key: dict[Any, tuple] = {}
        self._data_event: threading.Event | None = None
        # offset frontier snapshotted atomically with commit()/_drain():
        # the persisted frontier must cover EXACTLY the drained entries —
        # reading current_offsets() on the driver thread after _drain()
        # would race the reader (an entry committed in between would be
        # covered by the frontier but missing from the batch, i.e. lost
        # on restart)
        self._offsets_at_commit: Any = None
        self._offsets_at_drain: Any = None
        #: total commit() calls — the driver uses this to detect a
        #: tracking subject that never self-commits (see _live_loop)
        self._commit_count = 0
        #: set by the driver when persistence storage is configured —
        #: without it the frontier snapshot in commit() is never consumed,
        #: so the (possibly large) current_offsets() copy is skipped
        self._record_offsets = False
        # end-to-end freshness stamps (pathway_freshness_seconds): the
        # wall clock of the FIRST row read into the current pending
        # batch, carried through commit() and _drain() so the driver can
        # hand the earliest read time of each engine timestamp to the
        # freshness tracker — measuring from source READ, not from the
        # driver push, covers connector-side batching delay too
        self._pending_read_wall: float | None = None
        self._committed_read_walls: list[float] = []
        self._read_wall_at_drain: float | None = None

    # -- to be implemented by subclasses --
    def run(self) -> None:
        raise NotImplementedError

    def on_stop(self) -> None:
        """Called once the subject is done (reference: on_stop hook)."""

    @property
    def _deletions_enabled(self) -> bool:
        return True

    # -- emission API --
    def next(self, **kwargs: Any) -> None:
        values = tuple(kwargs.get(name) for name in self._column_names)
        key = self._derive_key(kwargs)
        self._push("insert", key, values)

    def next_json(self, message: dict | str | bytes) -> None:
        try:
            if isinstance(message, (str, bytes)):
                message = json.loads(message)
            if not isinstance(message, dict):
                raise TypeError(
                    f"expected a JSON object, got {type(message).__name__}"
                )
        except (ValueError, TypeError) as exc:
            if self._on_error == "dead_letter":
                self.dead_letter(message, exc)
                return
            raise
        self.next(**message)

    def dead_letter(self, payload: Any, exc: Exception | None = None) -> None:
        """Route a poison record out of the stream: it lands in
        ``pw.global_error_log()`` (kind ``dead_letter``) and every sink
        registered via ``pw.set_dead_letter_sink`` — the pipeline keeps
        consuming."""
        from ..internals.errors import dead_letter as _dead_letter

        reason = (
            f"{type(exc).__name__}: {exc}" if exc is not None else "poison record"
        )
        _dead_letter(payload, reason, source=self._datasource_name)

    def next_str(self, message: str) -> None:
        self.next(data=message)

    def next_bytes(self, message: bytes) -> None:
        self.next(data=message)

    def delete(self, **kwargs: Any) -> None:
        if not self._deletions_enabled:
            raise RuntimeError("deletions not enabled on this subject")
        values = tuple(kwargs.get(name) for name in self._column_names)
        key = self._derive_key(kwargs)
        self._push("delete", key, values)

    def _remove(self, key: Any, values: tuple) -> None:
        self._push("delete", key, values)

    def _add_inner(self, key: Any, values: tuple) -> None:
        self._push("insert", key, values)

    def commit(self) -> None:
        with self._lock:
            if self._pending:
                self._committed.append(self._pending)
                self._pending = []
                if self._pending_read_wall is not None:
                    self._committed_read_walls.append(self._pending_read_wall)
                    self._pending_read_wall = None
            # every connector updates its offsets before its own commit()
            # (fs: _seen per emitted file; kafka: per consumed message),
            # so this snapshot is exactly the frontier of the batches
            # committed so far.  Skipped without persistence: nobody
            # consumes it, and for fs it copies the whole _seen dict —
            # which a driver-thread autocommit could also race mid-resize
            # (tracking subjects only self-commit once persistence is on)
            if self._record_offsets:
                self._offsets_at_commit = self.current_offsets()
            self._commit_count += 1
        if self._data_event is not None:
            self._data_event.set()

    def close(self) -> None:
        self.commit()
        self._closed.set()
        if self._data_event is not None:
            self._data_event.set()

    # -- persistence hooks (reference: Reader::seek data_storage.rs:398 +
    # OffsetAntichain offsets; overridden by offset-aware subjects) --
    def current_offsets(self) -> Any:
        """Source position to persist with each snapshot chunk."""
        return None

    def seek(self, offsets: Any) -> None:
        """Restore the source position after snapshot replay."""

    def effective_persistent_id(self, occurrence: int | None = None) -> str | None:
        """Key for this subject's snapshot keyspace.

        An explicit ``persistent_id`` wins.  Otherwise a default is derived
        from the datasource name plus this subject's *occurrence number
        among same-named sources* (graph order), so two subjects with the
        same datasource name (two ``fs.read`` of one path, two custom
        python subjects) never share a keyspace, while adding an unrelated
        differently-named source does not shift existing keys.  Without an
        occurrence number no safe default exists and ``None`` is returned
        (persistence stays off for the subject)."""
        if self.persistent_id is not None:
            return self.persistent_id
        if occurrence is None:
            return None
        return f"{self._datasource_name}-{occurrence}"

    def _tracks_offsets(self) -> bool:
        """True when the subclass overrides offset tracking (capability, not
        the runtime value — a seek-capable source legitimately reports no
        offset before its first record)."""
        return type(self).current_offsets is not ConnectorSubject.current_offsets

    # -- plumbing --
    def _derive_key(self, kwargs: dict) -> Any:
        if self._primary_key:
            return ref_scalar(*[kwargs.get(c) for c in self._primary_key])
        return next_autogen_key(self._datasource_name)

    def _push(self, op: str, key: Any, values: tuple | None) -> None:
        if faults.enabled and self._fault_site is not None:
            # chaos harness: "fail" raises into the reader thread (the
            # supervisor's backoff territory), "drop" loses the row
            if faults.perturb(self._fault_site) == "drop":
                return
        with self._lock:
            if not self._pending:
                self._pending_read_wall = _time.time()
            self._pending.append((op, key, values))

    def _configure(self, schema, primary_key: list[str] | None) -> None:
        self._schema = schema
        self._column_names = list(schema.column_names())
        self._primary_key = primary_key

    def _attach(self, src: SourceNode, engine: Engine) -> None:
        self._src = src
        self._engine = engine

    def _drain(self) -> list[Entry]:
        """Convert committed batches to engine entries (upsert-aware)."""
        with self._lock:
            batches, self._committed = self._committed, []
            # pair the batch with the frontier of its last commit — a
            # commit landing after this point belongs to the NEXT drain
            self._offsets_at_drain = self._offsets_at_commit
            # earliest read time across the drained batches: the start of
            # the end-to-end freshness span for this engine timestamp
            walls, self._committed_read_walls = self._committed_read_walls, []
            self._read_wall_at_drain = min(walls) if walls else None
        entries: list[Entry] = []
        for batch in batches:
            for op, key, values in batch:
                if self._session_type == "upsert":
                    old = self._last_by_key.pop(key, None)
                    if old is not None:
                        entries.append((key, old, -1))
                    if op == "insert":
                        entries.append((key, values, 1))
                        self._last_by_key[key] = values
                else:
                    entries.append((key, values, 1 if op == "insert" else -1))
        return entries

    _static_entries: list[Entry] | None = None

    def _run_static(self, src: SourceNode) -> None:
        """Drain a static subject synchronously at time 0 (build time).

        The drained entries are cached so the same table can be
        materialized more than once (pw.debug preview + pw.run)."""
        if self._static_entries is None:
            self.run()
            self.close()
            self._static_entries = self._drain()
            self.on_stop()
        if self._static_entries:
            src.push(0, list(self._static_entries))


#: process-lifetime reader-restart counter (chaos soak reporting and
#: operational introspection) — survives finished runs' supervisors
_restart_total = 0


def connector_restart_total() -> int:
    """Total reader restarts across all supervised connectors so far."""
    return _restart_total


class ConnectorSupervisor:
    """Runs one subject's reader under supervision (reference inspiration:
    src/connectors/mod.rs reader threads, which on error poison the whole
    run — here a reader exception instead triggers exponential-backoff
    restarts, bounded by ``max_restarts``, with per-connector state
    surfaced on ``/v1/health``).

    Restart safety: connectors that dedupe (fs/http ``_seen``) or run
    upsert sessions re-enter ``run()`` cleanly; subjects that cannot set
    ``_supervised = False`` and keep the old die-silently behavior, minus
    the silence (the failure is logged and the connector marked failed).
    """

    #: after this long healthy, the restart budget refills
    BACKOFF_RESET_S = 60.0

    def __init__(self, subject: ConnectorSubject, label: str):
        self.subject = subject
        self.label = label
        self.restarts = 0
        self.max_restarts = subject._max_restarts
        if self.max_restarts is None:
            self.max_restarts = int(
                os.environ.get("PATHWAY_CONNECTOR_MAX_RESTARTS", "3")
            )
        self.backoff_s = float(
            os.environ.get("PATHWAY_CONNECTOR_BACKOFF_S", "0.1")
        )
        self.backoff_cap_s = float(
            os.environ.get("PATHWAY_CONNECTOR_BACKOFF_CAP_S", "30")
        )

    def _health(self):
        from ..internals.health import get_health

        return get_health()

    def _set_state(self, state: str, *, ready: bool = True,
                   degraded: bool = False, detail: str = "") -> None:
        # connectors are not individually critical for readiness: one
        # failed source must not mark an otherwise-serving process
        # unready — it shows as degraded instead
        self._health().set_component(
            f"connector:{self.label}", state,
            ready=ready, degraded=degraded, critical=False, detail=detail,
        )

    def run(self) -> None:
        """Reader-thread body: run → (on failure) backoff → rerun."""
        from ..internals.errors import register_error

        subject = self.subject
        attempt = 0
        delay = self.backoff_s
        while True:
            started = _time.monotonic()
            try:
                self._set_state("running")
                subject.run()
                self._set_state("finished")
                return
            except BaseException as exc:  # noqa: BLE001 — supervised
                if subject._closed.is_set():
                    # shutdown race: the failure is a consequence of
                    # closing, not a fault
                    self._set_state("finished")
                    return
                register_error(
                    f"connector {self.label!r} reader failed: "
                    f"{type(exc).__name__}: {exc}",
                    kind="connector",
                    operator=self.label,
                )
                if not subject._supervised:
                    self._set_state(
                        "failed", ready=True, degraded=True,
                        detail=f"unsupervised reader died: {exc}",
                    )
                    logger.error(
                        "connector %r reader died (unsupervised): %s",
                        self.label, exc,
                    )
                    return
                if _time.monotonic() - started > self.BACKOFF_RESET_S:
                    attempt = 0
                    delay = self.backoff_s
                if attempt >= self.max_restarts:
                    self.restarts = attempt
                    self._set_state(
                        "failed", ready=True, degraded=True,
                        detail=(
                            f"gave up after {attempt} restarts: "
                            f"{type(exc).__name__}: {exc}"
                        ),
                    )
                    logger.error(
                        "connector %r failed permanently after %d restarts: %s",
                        self.label, attempt, exc,
                    )
                    return
                attempt += 1
                self.restarts = attempt
                global _restart_total
                _restart_total += 1
                sleep_s = min(delay, self.backoff_cap_s) * (
                    1.0 + random.uniform(0.0, 0.25)
                )
                self._set_state(
                    "backoff", degraded=True,
                    detail=(
                        f"restart {attempt}/{self.max_restarts} in "
                        f"{sleep_s:.2f}s after {type(exc).__name__}: {exc}"
                    ),
                )
                logger.warning(
                    "connector %r reader failed (%s); restart %d/%d in %.2fs",
                    self.label, exc, attempt, self.max_restarts, sleep_s,
                )
                # responsive to shutdown: close() sets _closed
                if subject._closed.wait(sleep_s):
                    self._set_state("finished")
                    return
                delay = min(delay * 2.0, self.backoff_cap_s)


class StreamingDriver:
    """The run loop behind ``pw.run`` (reference: timely's
    ``worker.step_or_park`` pump, dataflow.rs:5689-5731, with connector
    pollers and commit flushers folded in).

    Starts one thread per streaming subject, then repeatedly drains
    committed batches, stamps them with the next micro-batch timestamp and
    advances the engine.  Terminates when every subject has closed and all
    buffers are empty; runs forever if any subject never closes.
    """

    def __init__(
        self,
        engine: Engine,
        runner,
        *,
        persistence_config: Any = None,
        monitoring_level: Any = None,
        with_http_server: bool = False,
        autocommit_ms: int = 20,
        exchange_plane: Any = None,
    ) -> None:
        self.engine = engine
        self.runner = runner
        self.autocommit_ms = autocommit_ms
        self.persistence_config = persistence_config
        self.exchange_plane = exchange_plane
        self.subject_src: list[tuple[ConnectorSubject, SourceNode]] = []
        #: subject -> occurrence number among same-named sources in graph
        #: order, used to derive unique yet stable default persistent ids
        self._pid_occurrence: dict[int, int] = {}
        name_counts: dict[str, int] = {}
        for src, op in runner.source_nodes:
            subject = op.params.get("subject")
            if subject is not None and subject._mode == "streaming":
                self.subject_src.append((subject, src))
                n = name_counts.get(subject._datasource_name, 0)
                name_counts[subject._datasource_name] = n + 1
                self._pid_occurrence[id(subject)] = n
        self._snapshot_writers: dict[int, Any] = {}
        #: OPERATOR_PERSISTING: subject-id -> (pid, subject), offsets ride
        #: the per-tick commit record instead of input snapshot chunks
        self._commit_subjects: dict[int, tuple] = {}
        self._op_snapshot = None
        #: subject-id -> ConnectorSupervisor (restart counts for soak/health)
        self.supervisors: dict[int, ConnectorSupervisor] = {}

    def _snapshot_storage(self):
        """KV storage when full persistence is on (not UDF-caching-only)."""
        cfg = self.persistence_config
        if cfg is None:
            return None
        from ..persistence import PersistenceMode

        if cfg.persistence_mode in (
            PersistenceMode.PERSISTING,
            PersistenceMode.OPERATOR_PERSISTING,
        ):
            return cfg.backend.storage
        return None

    @property
    def _operator_mode(self) -> bool:
        """OPERATOR_PERSISTING: stateful-operator state recovers from the
        chunked snapshot plane (O(delta) per commit); input entries are
        never logged — a single post-step commit record (``commit/record``)
        carries the finalized time + offset frontier, and restart seeks
        rather than replays (replaying on top of restored operator state
        would double every record)."""
        cfg = self.persistence_config
        if cfg is None:
            return False
        from ..persistence import PersistenceMode

        return cfg.persistence_mode is PersistenceMode.OPERATOR_PERSISTING

    def _setup_persistence(self, t: int, step: bool = True) -> int:
        """Replay input snapshots, seek subjects, restore operator state
        (reference: Entry::{Snapshot,RewindFinishSentinel} replay,
        src/connectors/mod.rs:100-104; reader seek data_storage.rs:398;
        operator_snapshot.rs).  ``step=False`` leaves the replayed rows
        queued for the caller's own (barrier-synchronized) stepping."""
        storage = self._snapshot_storage()
        if storage is None:
            return t
        from ..persistence import (
            ChunkedOperatorSnapshot,
            InputSnapshotReader,
            InputSnapshotWriter,
        )

        self._op_snapshot = ChunkedOperatorSnapshot(storage)
        operator_mode = self._operator_mode
        commit_rec = None
        if operator_mode:
            self._check_operator_mode_coverage()
            raw = storage.get(self._commit_record_key())
            if raw is not None:
                import pickle as _pickle

                commit_rec = _pickle.loads(raw)
        pushed = False
        for subject, src in self.subject_src:
            # Opt-in contract (reference: persistent_id on connectors):
            # snapshotting a subject that cannot seek would replay its
            # snapshot AND let run() re-produce the same rows from scratch,
            # doubling every record — so gate on offset tracking or an
            # explicit persistent_id.
            if subject.persistent_id is None and not subject._tracks_offsets():
                continue
            pid = subject.effective_persistent_id(self._pid_occurrence.get(id(subject)))
            if pid is None:
                continue
            # multi-process runs share one backend storage: scope each
            # process's snapshot keyspace so shard-filtered batches don't
            # clobber each other's chunk counters (reference: worker-keyed
            # snapshots, src/persistence/input_snapshot.rs:56-283)
            if self.exchange_plane is not None:
                pid = f"{pid}-p{self.exchange_plane.me}"
            # this subject's commit() frontier now has a consumer (input
            # snapshot chunks or the per-tick commit record)
            subject._record_offsets = True
            if operator_mode:
                # offsets live in the per-tick commit record, written
                # AFTER the operator deltas are durable — entries are
                # never logged, so there is nothing to replay
                self._commit_subjects[id(subject)] = (pid, subject)
                if commit_rec is not None:
                    offsets = commit_rec["offsets"].get(pid)
                    if offsets is not None:
                        subject.seek(offsets)
                        # seed the drain frontier: the next commit record
                        # must carry this restored position forward, not
                        # clobber it with None before the subject's first
                        # own commit (a crash in that window would lose
                        # the frontier and double-apply the whole source)
                        subject._offsets_at_commit = offsets
                        subject._offsets_at_drain = offsets
                continue
            reader = InputSnapshotReader(storage, pid)
            replayed: list[Entry] = []
            for entries in reader.replay():
                replayed.extend(entries)
            if replayed:
                src.push(t, replayed)
                pushed = True
            offsets = reader.last_offsets()
            if offsets is not None:
                subject.seek(offsets)
            self._snapshot_writers[id(subject)] = InputSnapshotWriter(storage, pid)
        # restore stateful-operator snapshots before any replayed data flows
        from ..internals.engine import DeduplicateNode, GroupByNode, ZipNode

        committed_t = commit_rec["time"] if commit_rec is not None else 0
        restored_t = 0
        for node in self.engine.nodes:
            if (
                isinstance(node, (DeduplicateNode, GroupByNode, ZipNode))
                and node.persistent_id
            ):
                if isinstance(node, (GroupByNode, ZipNode)) and not operator_mode:
                    # groupby/zip state is rebuilt by input replay in
                    # PERSISTING mode; only OPERATOR_PERSISTING restores
                    # (and writes) it through the snapshot plane
                    continue
                # per-process keyspace, same as the input snapshots
                node.persistent_id = self._scoped_pid(node.persistent_id)
                # single scan: drops a crashed run's uncommitted tail (its
                # input offsets were never recorded, so the batch re-reads
                # and would double-apply on top of orphaned chunks) and
                # replays base+deltas in one pass over the store
                state, last_t = self._op_snapshot.restore(
                    node.persistent_id,
                    committed_time=committed_t if operator_mode else None,
                )
                if state is not None:
                    node.restore_snapshot(state)
                restored_t = max(restored_t, last_t)
                node._op_snapshot = self._op_snapshot
        if operator_mode:
            restored_t = max(
                restored_t, self._restore_index_nodes(committed_t)
            )
        if operator_mode and commit_rec is not None:
            self._op_snapshot.mark_committed(committed_t)
            t = max(t, committed_t + 1)
        # EVERY mode: resume engine time past the newest restored delta —
        # chunk replay orders deltas by finalized time, so a fresh run
        # re-using earlier times (engine times restart from 1) would make
        # a stale previous-run delta win on the next restore
        t = max(t, restored_t + 1)
        if pushed and step:
            self.engine.step(t)
            t += 1
        return t

    def _commit_record_key(self) -> str:
        if self.exchange_plane is not None:
            return f"commit/record-p{self.exchange_plane.me}"
        return "commit/record"

    def _scoped_pid(self, pid: str) -> str:
        """Per-process snapshot keyspace in multi-process runs: append
        ``-p{me}`` (idempotent) so shard-filtered state never clobbers
        another process's chunk counters (reference: worker-keyed
        snapshots, src/persistence/input_snapshot.rs:56-283)."""
        if self.exchange_plane is None:
            return pid
        suffix = f"-p{self.exchange_plane.me}"
        return pid if pid.endswith(suffix) else f"{pid}{suffix}"

    def _restore_index_nodes(self, committed_t: int) -> int:
        """Warm-restart the live vector index behind a health gate
        (OPERATOR_PERSISTING): stream each covered ``ExternalIndexNode``'s
        snapshot chunks back into HBM via one bulk upsert — zero encoder
        calls — while ``/v1/health`` reports ``index: restoring`` and the
        serving plane answers from the degraded lexical mirror instead of
        503ing.  Chunk reads retry through the seeded ``index.restore``
        fault site; a store that stays unreadable fails the run loudly
        (serving silently empty would look like data loss).  Returns the
        newest restored finalized time (the driver resumes engine time
        past it)."""
        from ..internals.errors import register_error
        from ..internals.flight_recorder import record_span
        from ..internals.health import get_health
        from ..stdlib.indexing.lowering import ExternalIndexNode

        health = get_health()
        newest = 0
        attempts = max(1, int(os.environ.get("PATHWAY_RESTORE_ATTEMPTS", "3")))
        for node in self.engine.nodes:
            if not isinstance(node, ExternalIndexNode) or not node.persistent_id:
                continue
            # per-process keyspace, same as the zip/groupby loop above
            # (defense-in-depth: OPERATOR_PERSISTING is refused in
            # multi-process runs today, but the keyspaces must not
            # collide the day that restriction lifts)
            node.persistent_id = self._scoped_pid(node.persistent_id)
            pid = node.persistent_id
            node._op_snapshot = self._op_snapshot
            comp = f"index:{pid}"
            progress = {"chunks": 0, "entries": 0}

            def on_chunk(key, n, ms, progress=progress, pid=pid):
                progress["chunks"] += 1
                progress["entries"] += n
                health.set_restore(
                    pid, state="restoring",
                    chunks_replayed=progress["chunks"],
                )
                record_span(
                    "restore:chunk", "restore", _time.time(), ms,
                    attrs={"key": key, "entries": n, "index": pid},
                )

            node._restore_state = "restoring"
            health.set_component(
                comp, "restoring", ready=True, degraded=True, critical=False,
                detail="streaming snapshot chunks into the index",
            )
            health.set_restore(
                pid, state="restoring", chunks_replayed=0, rows_restored=0,
            )
            wall = _time.time()
            t0 = _time.monotonic()
            state = None
            last_t = 0
            last_exc: BaseException | None = None
            for attempt in range(attempts):
                progress["chunks"] = progress["entries"] = 0
                try:
                    if faults.enabled:
                        faults.perturb("index.restore")
                    state, last_t = self._op_snapshot.restore(
                        pid, committed_time=committed_t, on_chunk=on_chunk
                    )
                    last_exc = None
                    break
                except Exception as exc:  # noqa: BLE001 — bounded retry
                    last_exc = exc
                    register_error(
                        f"index {pid!r} restore attempt {attempt + 1}/"
                        f"{attempts} failed: {type(exc).__name__}: {exc}",
                        kind="index",
                        operator=pid,
                    )
            if last_exc is not None:
                node._restore_state = None
                health.set_component(
                    comp, "restore_failed", ready=False, degraded=True,
                    detail=f"{type(last_exc).__name__}: {last_exc}",
                )
                health.set_restore(pid, state="failed")
                raise RuntimeError(
                    f"index {pid!r} could not restore its snapshot after "
                    f"{attempts} attempts — refusing to serve an empty "
                    "index over durable state (clear the store to rebuild "
                    f"from replay). Last error: "
                    f"{type(last_exc).__name__}: {last_exc}"
                ) from last_exc
            # routing spec first: the delta-chunk header carries the LSH
            # projector / partition-router specs, and the index must
            # route (and partition) the restored rows exactly as the
            # process that wrote them did
            header = self._op_snapshot.last_restored_header(pid)
            if header:
                node.apply_snapshot_header(header)
            if state:
                node.restore_snapshot(state)
            node._restore_state = None
            duration_ms = (_time.monotonic() - t0) * 1000.0
            health.set_component(
                comp, "ok", ready=True, degraded=False, critical=False,
            )
            health.set_restore(
                pid, state="ok",
                chunks_replayed=progress["chunks"],
                rows_restored=node.restored_rows,
                duration_ms=round(duration_ms, 3),
            )
            # a mesh-sharded index re-pins restored rows to its shards
            # through the placement-preserving scatter; surface the
            # resulting per-shard layout so a warm restart's balance is
            # observable next to its chunk/row counts
            inner = getattr(node.index, "index", None)
            if inner is not None and hasattr(inner, "shard_row_counts"):
                health.set_restore(
                    pid,
                    mesh_devices=int(inner.n_shards),
                    rows_per_shard=inner.shard_row_counts(),
                )
            record_span(
                f"restore:{pid}", "restore", wall, duration_ms,
                attrs={
                    "chunks": progress["chunks"],
                    "rows": node.restored_rows,
                    "index": pid,
                },
            )
            newest = max(newest, last_t)
        return newest

    def _check_operator_mode_coverage(self) -> None:
        """OPERATOR_PERSISTING replays no input entries, so every stateful
        node must recover from the snapshot plane — refuse the mode when
        the graph holds stateful nodes it does not cover, instead of
        silently restarting them empty."""
        from ..internals.engine import (
            AsyncMapNode,
            BufferNode,
            DeduplicateNode,
            GroupByNode,
            JoinNode,
            RowwiseNode,
            SemiJoinNode,
            UpdateCellsNode,
            UpdateRowsNode,
            ZipNode,
        )
        from ..stdlib.indexing.lowering import ExternalIndexNode, SortNode

        if self.exchange_plane is not None:
            raise RuntimeError(
                "PersistenceMode.OPERATOR_PERSISTING is not supported in "
                "multi-process runs yet — the pipelined exchange completes "
                "rounds out of band, so there is no single point to record "
                "the committed offset frontier. Use "
                "PersistenceMode.PERSISTING (input replay) instead."
            )
        # sources too: a subject that opts out of persistence re-produces
        # every row from scratch on restart — harmless under input replay
        # (the state is rebuilt from the same rows), but on top of RESTORED
        # operator state it double-applies everything
        unseekable = []
        for subject, _src in self.subject_src:
            if subject._ephemeral:
                # request-scoped sources (REST handlers): their rows are
                # in-flight HTTP requests, gone with the process — there
                # is nothing to restore and nothing to double-apply
                # (clients retry); they are exempt from seekability
                continue
            pid = subject.effective_persistent_id(
                self._pid_occurrence.get(id(subject))
            )
            # an explicit persistent_id does NOT make a source seekable —
            # without offset tracking there is no frontier to seek to, and
            # run() re-produces every row on top of RESTORED operator state
            if pid is None or not subject._tracks_offsets():
                unseekable.append(subject._datasource_name)
        if unseekable:
            raise RuntimeError(
                "PersistenceMode.OPERATOR_PERSISTING restores operator "
                "state without replaying inputs, so every source must be "
                "seekable; these are not: "
                f"{', '.join(sorted(unseekable))}. Give them a "
                "persistent_id (and offset tracking), or use "
                "PersistenceMode.PERSISTING."
            )
        uncovered = []
        for node in self.engine.nodes:
            if isinstance(node, (DeduplicateNode, GroupByNode, ZipNode)):
                if not node.persistent_id:
                    uncovered.append(f"{node.name} (no persistent_id)")
            elif isinstance(node, ExternalIndexNode):
                # asof_now index nodes are first-class recovery citizens:
                # their doc state (already-computed vectors + payloads)
                # checkpoints through the chunked snapshot plane and
                # restores via one bulk upsert.  live-mode nodes stay
                # refused — their refresh contract needs the live query
                # rows, which this mode never replays
                if node.mode != "asof_now" or not node.persistent_id:
                    uncovered.append(f"{node.name} (live-mode index)")
            elif isinstance(node, AsyncMapNode):
                # the only cross-step state is the retraction memo: with
                # every slot UDF deterministic, an empty memo recomputes
                # identical values — safe to restart uncovered
                if not getattr(node, "_slots_deterministic", False):
                    uncovered.append(
                        f"{node.name} (non-deterministic async map)"
                    )
            elif isinstance(
                node,
                # every node whose flush() folds input into cross-step
                # state: restarting it empty on top of restored downstream
                # state silently corrupts results (missing retractions,
                # empty indexes, unpaired non-deterministic recomputes)
                (JoinNode, BufferNode, UpdateRowsNode,
                 UpdateCellsNode, SemiJoinNode, SortNode),
            ):
                uncovered.append(node.name)
            elif isinstance(node, RowwiseNode) and node.memoize:
                # memoized maps exist precisely because the fn is
                # non-deterministic: an empty memo after restart would
                # recompute a different row for a retraction and unpair it
                uncovered.append(f"{node.name} (memoized non-deterministic map)")
        if uncovered:
            raise RuntimeError(
                "PersistenceMode.OPERATOR_PERSISTING cannot recover these "
                f"stateful operators: {', '.join(sorted(uncovered))}. Give "
                "groupby/deduplicate operators a persistent_id, or use "
                "PersistenceMode.PERSISTING (input replay covers every "
                "operator)."
            )

    def _write_commit_record(self, t: int) -> None:
        """Durably record the finalized time and every subject's offset
        frontier — AFTER the tick's operator deltas are on disk.  A crash
        before this write replays the batch against truncated chunks
        (exactly-once); writing offsets first instead would drop the
        batch entirely."""
        storage = self._snapshot_storage()
        if storage is None or not self._commit_subjects:
            return
        import pickle as _pickle

        offsets = {
            pid: subject._offsets_at_drain
            for pid, subject in self._commit_subjects.values()
        }
        storage.put(
            self._commit_record_key(),
            _pickle.dumps({"time": t, "offsets": offsets}),
        )
        self._op_snapshot.mark_committed(t)
        from ..internals.health import get_health

        get_health().note_commit()

    def run(self) -> None:
        from ..internals.health import get_health

        health = get_health()
        health.begin_run()
        health.set_component("engine", "running", ready=True)
        health.beat("engine")
        if self.exchange_plane is not None:
            self._run_distributed()
            return
        if not self.subject_src:
            self.engine.run_all()
            health.set_component("engine", "finished", ready=True)
            return
        data_event = threading.Event()
        # statically-fed sources (debug tables, static subjects) queued rows
        # at build time — drain those timestamps before going live, or a
        # mixed static+streaming graph would never process them
        static_times = sorted(
            {t for s in self.engine.sources for t in s.pending_times()}
        )
        for t0 in static_times:
            self.engine.step(t0)
        t = self._setup_persistence(max(static_times, default=0) + 1)
        threads = self._start_connector_threads(data_event)

        from ..internals.engine import gc_batch_mode

        last_autocommit = {id(s): _time.monotonic() for s, _ in self.subject_src}
        with gc_batch_mode():
            self._live_loop(data_event, t, last_autocommit)
        self._record_finished_connectors()
        self.engine.finish()
        from ..internals.health import get_health

        get_health().set_component("engine", "finished", ready=True)

    def _live_loop(self, data_event, t, last_autocommit) -> None:
        from ..internals.health import get_health

        health = get_health()
        loop_start = _time.monotonic()
        warned_stalled: set[int] = set()
        while True:
            data_event.wait(timeout=self.autocommit_ms / 1000.0)
            data_event.clear()
            # engine watchdog: a wedged loop stops beating and /v1/health
            # flips unready after health.engine_stall_s
            health.beat("engine")
            now = _time.monotonic()
            persisting = self._snapshot_storage() is not None
            for subject, _src in self.subject_src:
                ac = subject._autocommit_ms
                # under persistence, offset-tracking subjects commit on
                # their own reader thread at consistent boundaries (fs: end
                # of scan, kafka: per message); a driver-thread commit could
                # snapshot a mid-unit frontier that pairs rows already in
                # the batch with an offset that re-reads them on restart.
                # Without persistence no frontier is recorded, so driver
                # autocommit stays on (external ConnectorSubject subclasses
                # may override current_offsets yet rely on it)
                if persisting and subject._tracks_offsets():
                    # a tracking subject that NEVER self-commits would
                    # stall silently here — surface it once, loudly
                    if (
                        ac is not None
                        and subject._commit_count == 0
                        and id(subject) not in warned_stalled
                        and (now - loop_start) * 1000 >= 20 * max(ac, 1500)
                    ):
                        warned_stalled.add(id(subject))
                        import warnings

                        warnings.warn(
                            f"connector {subject._datasource_name!r} tracks "
                            "offsets but has not committed once: under "
                            "persistence the driver never autocommits "
                            "offset-tracking subjects (a driver-paced "
                            "frontier could re-read committed rows after "
                            "restart) — call self.commit() from the "
                            "connector at consistent source boundaries",
                            RuntimeWarning,
                            stacklevel=1,
                        )
                    continue
                if ac is not None and (now - last_autocommit[id(subject)]) * 1000 >= ac:
                    subject.commit()
                    last_autocommit[id(subject)] = now
            pushed = False
            for subject, src in self.subject_src:
                entries = subject._drain()
                if entries:
                    src.push(t, entries)
                    self._write_snapshot(subject, entries)
                    self._record_connector(subject, len(entries), t)
                    pushed = True
            # a finite source next to an unbounded one must report finished
            # while the run continues (reference: ConnectorMonitor finish)
            self._record_finished_connectors()
            if pushed:
                self.engine.step(t)
                self._write_commit_record(t)
                t += 1
                continue
            if self.engine.has_async_ready() or (
                self.persistence_config is not None
                and self.engine.has_placement_flush_pending()
            ):
                # step once while sources are idle: a pipelined async
                # batch resolved (its results should emit now, not at
                # the next input), or a tiered index migrated under pure
                # query traffic (end_of_step must stage + persist the
                # new placement — waiting for input could be forever)
                self.engine.step(t)
                self._write_commit_record(t)
                t += 1
                continue
            if all(s._closed.is_set() for s, _ in self.subject_src):
                # final drain to catch a close() racing the check
                for subject, src in self.subject_src:
                    entries = subject._drain()
                    if entries:
                        src.push(t, entries)
                        self._write_snapshot(subject, entries)
                        self._record_connector(subject, len(entries), t)
                        pushed = True
                if pushed:
                    self.engine.step(t)
                    self._write_commit_record(t)
                    t += 1
                break

    def _write_snapshot(self, subject: ConnectorSubject, entries: list[Entry]) -> None:
        # OPERATOR_PERSISTING never registers writers: its offsets are
        # recorded post-step by _write_commit_record, and entries are
        # never logged (operator deltas carry the state)
        writer = self._snapshot_writers.get(id(subject))
        if writer is not None:
            # the drain-time frontier, not current_offsets(): the reader
            # may already have committed entries this batch doesn't hold
            writer.write_batch(entries, subject._offsets_at_drain)

    # -- per-connector progress (reference: connectors/monitoring.rs) --
    def _connector_label(self, subject: ConnectorSubject) -> str:
        idx = self._pid_occurrence.get(id(subject), 0)
        return f"{subject._datasource_name}-{idx}"

    def _record_connector(
        self, subject: ConnectorSubject, n: int, t: int | None = None
    ) -> None:
        label = self._connector_label(subject)
        monitor = getattr(self.engine, "monitor", None)
        if monitor is not None:
            monitor.record_connector_commit(label, n)
        import time as _time_mod

        from ..internals.flight_recorder import record_span
        from ..internals.monitoring import get_freshness

        now = _time_mod.time()
        # commit event into the flight recorder (works without a monitor)
        record_span(
            f"commit:{label}", "connector", now, 0.0,
            attrs={"messages": n, "t": t},
        )
        if t is not None:
            # freshness watermark: these rows entered at `now` under engine
            # timestamp `t`; when an index node applies timestamp `t` the
            # ingest->queryable lag becomes observable
            # (pathway_index_freshness_seconds).  Scoped by engine id —
            # timestamps restart per engine
            get_freshness().note_ingest(t, now, scope=id(self.engine))
            # end-to-end variant: the earliest CONNECTOR READ time of the
            # drained batches — closes as
            # pathway_freshness_seconds{connector=} when the index
            # applies timestamp t (read→parse→split→embed→upsert→commit)
            read_wall = getattr(subject, "_read_wall_at_drain", None)
            if read_wall is not None:
                get_freshness().note_source(
                    label, t, read_wall, scope=id(self.engine)
                )
            # fleet watermark hook: the subject learns the engine
            # timestamp its drained rows ride under, so the member can
            # flip the matching ingest watermark to QUERYABLE when an
            # index applies t (fleet/member.py)
            on_drained = getattr(subject, "_on_drained", None)
            if on_drained is not None:
                try:
                    on_drained(t, id(self.engine))
                except Exception:  # noqa: BLE001 — hooks must not stall the drain
                    pass

    def _record_finished_connectors(self) -> None:
        monitor = getattr(self.engine, "monitor", None)
        if monitor is not None:
            for subject, _src in self.subject_src:
                if subject._closed.is_set():
                    monitor.record_connector_finished(self._connector_label(subject))

    def _start_connector_threads(self, data_event=None) -> list:
        threads = []
        for subject, _src in self.subject_src:
            if data_event is not None:
                subject._data_event = data_event
            supervisor = ConnectorSupervisor(
                subject, self._connector_label(subject)
            )
            self.supervisors[id(subject)] = supervisor

            def runner(s=subject, sup=supervisor):
                try:
                    sup.run()
                finally:
                    s.close()
                    s.on_stop()

            th = threading.Thread(target=runner, daemon=True, name="pw-connector")
            th.start()
            threads.append(th)
        return threads

    # -- multi-process run loop (reference: timely Cluster workers stepping
    # in lockstep; dataflow/config.rs:71-120 + worker-architecture doc) --
    def _run_distributed(self) -> None:
        from ..internals.engine import gc_batch_mode

        with gc_batch_mode():
            self._run_distributed_inner()

    def _run_distributed_inner(self) -> None:
        from ..internals.exchange import owner_of

        plane = self.exchange_plane

        # statically-fed sources (debug rows, static subjects): keep only
        # this process's shard of keys when every process sees identical
        # data, and lift time-0 rows to round 1 (rounds start at 1); later
        # explicit __time__ stamps align with their round number natively
        for src, op in self.runner.source_nodes:
            subject = op.params.get("subject")
            is_static = subject is None or getattr(subject, "_mode", None) == "static"
            if not is_static:
                continue
            if subject is None or subject._shared_source:
                for t0, entries in list(src.queue.items()):
                    src.queue[t0] = [
                        e for e in entries if owner_of(e[0], plane.n) == plane.me
                    ]
            if 0 in src.queue:
                src.queue[1] = src.queue.pop(0) + src.queue.get(1, [])
        # rounds may not stop before the last statically-stamped timestamp
        # (identical on every process, so the bound is symmetric)
        max_static = max(
            (x for s in self.engine.sources for x in s.pending_times()),
            default=0,
        )
        # snapshot replay + seek must complete before connector threads run
        # (seek after a source began scanning would double records; and the
        # startup current_offsets() probe may not race the reader thread)
        self._setup_persistence(1, step=False)
        threads = self._start_connector_threads()

        # asynchronous progress: stage 1 of a round (drain sources,
        # flush the ingest-safe subgraph, partition + SEND first-hop
        # exchange batches and the control flag) may run up to W rounds
        # ahead of the oldest unfinished round, so a straggler's slow
        # rounds overlap the fast workers' later ingest instead of
        # serializing the whole cluster per round (the role timely's
        # frontier-based progress tracking plays in the reference);
        # stage 2 (receive + stateful flush) completes rounds in order.
        from ..internals.exchange import ingest_safe_nodes, wavefront_requirements

        safe_ids, first_hop = ingest_safe_nodes(self.engine)
        safe_frozen = frozenset(safe_ids)
        ex_list, req_start, reqs, ups = wavefront_requirements(
            self.engine, safe_ids
        )
        # the lookahead window counts DATA-CARRYING rounds (real memory);
        # empty ticks are nearly free (a few control frames) and get a
        # separate, much larger cap — otherwise at a 20 ms tick the
        # window fills with empty rounds in a fraction of a second and
        # later batches have no in-flight round to land in
        lookahead = max(
            1, int(os.environ.get("PATHWAY_EXCHANGE_LOOKAHEAD", "4"))
        )
        max_rounds = max(
            lookahead,
            int(os.environ.get("PATHWAY_EXCHANGE_MAX_ROUNDS", "512")),
        )
        if plane.n == 1 or (not first_hop and not reqs):
            # no peers to straggle / nothing can overlap — lookahead
            # would only add dead output latency
            lookahead = 1
            max_rounds = 1

        from collections import deque

        inflight: deque[tuple[int, bool, bool]] = deque()  # (t, done, has_data)
        t_next = 1

        def ingest_round() -> None:
            # pacing is the CALLER's job (the wavefront loop ticks this on
            # the autocommit cadence instead of sleeping here, so a
            # lookahead window never serializes W sleeps ahead of stage 2)
            nonlocal t_next
            t = t_next
            had_data = False
            persisting = self._snapshot_storage() is not None
            for subject, _src in self.subject_src:
                # under persistence, tracking subjects self-commit at
                # consistent boundaries (see _live_loop) — a driver commit
                # could pair a batch with a mid-unit offset frontier
                if subject._autocommit_ms is not None and not (
                    persisting and subject._tracks_offsets()
                ):
                    subject.commit()
            # read the closed flags BEFORE draining: close() commits its
            # final rows first, so a True flag means this round's drain
            # saw everything
            local_closed = all(
                s._closed.is_set() for s, _ in self.subject_src
            ) if self.subject_src else True
            for subject, src in self.subject_src:
                entries = subject._drain()
                if subject._shared_source:
                    entries = [
                        e for e in entries
                        if owner_of(e[0], plane.n) == plane.me
                    ]
                if entries:
                    src.push(t, entries)
                    self._write_snapshot(subject, entries)
                    self._record_connector(subject, len(entries), t)
                    had_data = True
            done = local_closed and t >= max_static
            # the control flag rides ahead with the data plane; every
            # process still sees the same flag set for round t
            plane.send(
                "__ctl__", t,
                {p: [done] for p in range(plane.n) if p != plane.me},
                is_entries=False,
            )
            # static rows queued directly on sources also make a round
            # data-carrying (flow control must bound their memory too)
            had_data = had_data or any(
                src.has_pending(t) for src in self.engine.sources
            )
            self.engine.step_ingest(t, safe_ids, first_hop)
            with inflight_lock:
                inflight.append((t, done, had_data))
            t_next += 1

        # --- cross-round wavefront (VERDICT r3 #4) -------------------
        # Each inflight round owns a resumable engine.step_iter generator
        # that yields at every exchange flush.  Rounds advance oldest
        # first; round t+1 may start (or resume past yield k) only once
        # round t has passed req_start (reqs[k]) exchanges — the static
        # guards from wavefront_requirements that keep every node's
        # timestamp order intact.  At each yield the exchange's batches
        # are SENT immediately, so a downstream exchange ships round
        # t+1's data while an upstream straggler still completes t —
        # previously chained exchanges (groupby→join) fell back to
        # lockstep here.

        _INF = float("inf")

        class _Round:
            __slots__ = ("t", "gen", "started", "waiting", "passed",
                         "finished", "blocked_since")

            def __init__(self, t, gen):
                self.t = t
                self.gen = gen
                self.started = False
                self.waiting = None  # exchange node at the current yield
                self.passed = 0
                self.finished = False
                self.blocked_since = None

        def _resume(r: "_Round") -> None:
            try:
                node = r.gen.send(None)
            except StopIteration:
                r.finished = True
                r.waiting = None
                return
            r.waiting = node
            # send NOW: input for this round is settled (the generator
            # only yields after quiescence); receivers buffer by time
            node.prepare(r.t)
            # eager prepare: any LATER exchange whose whole upstream has
            # already been passed can no longer receive round-r input —
            # snapshot and SEND its batch immediately, so peers stop
            # waiting on it even though this round's own yield is still
            # several hops away (e.g. the sums-side join input while the
            # counts side stalls)
            for k2 in range(r.passed + 1, len(ex_list)):
                if ups[k2] <= r.passed and not ex_list[k2].broadcast:
                    ex_list[k2].prepare(r.t)

        rounds: deque[_Round] = deque()
        # peers' done flags, consumed eagerly so the wavefront can know
        # the FINAL round before running past it: rounds after the
        # globally-done round must never start, or processes would finish
        # at different frontiers and desync the finish()-time exchange
        ctl_cache: dict[int, list] = {}

        def _ctl_ready(t: int) -> bool:
            if t in ctl_cache:
                return True
            if plane.poll("__ctl__", t):
                ctl_cache[t] = plane.recv("__ctl__", t)
                return True
            return False

        def _globally_done(i: int) -> bool:
            t, done_local, _data = inflight[i]
            return done_local and t in ctl_cache and all(ctl_cache[t])

        def _try_advance(i: int) -> bool:
            r = rounds[i]
            prev = rounds[i - 1] if i > 0 else None

            def prev_ok(need) -> bool:
                if prev is None or prev.finished:
                    return True
                need_prepared, need_passed = need
                if need_prepared == _INF or need_passed == _INF:
                    return False  # requires prev to fully finish
                if prev.passed < need_passed:
                    return False
                # prepared-or-flushed, queried per exchange: eager
                # prepares (in _resume) may run far ahead of prev's yield
                for k2 in range(int(need_prepared)):
                    e = ex_list[k2]
                    if prev.t not in e._prepared and e.has_pending(prev.t):
                        return False
                return True

            prog = False
            while not r.finished:
                if not r.started:
                    if prev is not None and (
                        not _ctl_ready(prev.t) or _globally_done(i - 1)
                    ):
                        # don't run past the last real round: every
                        # process must stop at the same frontier
                        break
                    if not prev_ok(req_start):
                        break
                    r.started = True
                    _resume(r)
                elif r.waiting is not None:
                    k = r.passed
                    ready = prev_ok(reqs[k]) and plane.poll(
                        r.waiting.channel, r.t
                    )
                    if not ready:
                        if r.blocked_since is None:
                            r.blocked_since = _time.monotonic()
                        elif (
                            i == 0
                            and _time.monotonic() - r.blocked_since
                            > plane.barrier_timeout
                        ):
                            # hung-but-connected peer: force the flush so
                            # recv raises its descriptive TimeoutError
                            # instead of parking forever
                            r.blocked_since = None
                            r.passed += 1
                            _resume(r)
                            prog = True
                            continue
                        break
                    r.blocked_since = None
                    r.passed += 1
                    _resume(r)
                else:  # pragma: no cover — finished handled by loop guard
                    break
                prog = True
            return prog

        # --- stage-1 ingest thread ----------------------------------
        # A slow operator (long UDF) blocks the engine thread mid-round;
        # if ingest ran on the same thread, this process would also stop
        # shipping ctl flags + first-hop batches for LATER rounds, and
        # every peer's wavefront would stall on us (the reference keeps
        # connector/commit machinery off the worker threads for the same
        # reason, src/connectors/mod.rs reader threads + commit ticks).
        # The ingest thread owns: subjects, source queue pushes, the
        # ingest-safe subgraph (step_ingest), first-hop prepares and ctl
        # sends.  The engine thread never touches those (step_iter skips
        # safe_ids), so the two domains are disjoint; `inflight` hands
        # rounds over under a lock.
        autocommit_s = self.autocommit_ms / 1000.0
        inflight_lock = threading.Lock()
        stop_ingest = threading.Event()
        ingest_error: list[BaseException] = []

        from ..internals.health import get_health

        health = get_health()
        health.set_component("ingest_thread", "running", ready=True)

        def ingest_loop() -> None:
            try:
                while not stop_ingest.is_set():
                    health.beat("ingest_thread")
                    with inflight_lock:
                        data_inflight = sum(1 for e in inflight if e[2])
                        total = len(inflight)
                    if data_inflight >= lookahead or total >= max_rounds:
                        _time.sleep(0.005)
                        continue
                    _time.sleep(autocommit_s)
                    if stop_ingest.is_set():
                        return
                    ingest_round()
            except BaseException as exc:  # noqa: BLE001 — surfaced by main
                ingest_error.append(exc)
                health.set_component(
                    "ingest_thread", "dead", ready=False,
                    detail=f"{type(exc).__name__}: {exc}",
                )

        ingest_thread = threading.Thread(target=ingest_loop, daemon=True)
        ingest_thread.start()
        try:
            while True:
                health.beat("engine")
                if ingest_error:
                    raise ingest_error[0]
                with inflight_lock:
                    n_inflight = len(inflight)
                    new_rounds = [
                        inflight[i][0] for i in range(len(rounds), n_inflight)
                    ]
                for t_new in new_rounds:
                    rounds.append(
                        _Round(
                            t_new,
                            self.engine.step_iter(t_new, skip_ids=safe_frozen),
                        )
                    )
                if not rounds:
                    plane.wait_any(0.02)
                    continue
                progressed = False
                for i in range(len(rounds)):
                    if _try_advance(i):
                        progressed = True
                if rounds and rounds[0].finished:
                    rounds.popleft()
                    with inflight_lock:
                        t, done, _data = inflight.popleft()
                    while not _ctl_ready(t):
                        plane.wait_any(0.05)
                    peer_flags = ctl_cache.pop(t)
                    if done and all(f for f in peer_flags):
                        break
                    continue
                if not progressed:
                    # every round is blocked on peer data — park until
                    # inbox activity (bounded so liveness checks re-run)
                    plane.wait_any(0.05)
        finally:
            stop_ingest.set()
            ingest_thread.join(timeout=10)
            if ingest_thread.is_alive():
                # a stuck reader (hung socket, wedged commit) leaks a live
                # daemon thread that keeps draining subjects after "exit":
                # say so loudly and pin it on /v1/health instead of
                # silently returning
                from ..internals.errors import register_error

                detail = (
                    "ingest thread failed to stop within 10s — leaked a "
                    "live thread still draining connector subjects"
                )
                logger.error("%s", detail)
                register_error(detail, kind="connector", operator="ingest_thread")
                health.set_component(
                    "ingest_thread", "leaked", ready=False, detail=detail
                )
            else:
                health.set_component("ingest_thread", "stopped", ready=True)
        self._record_finished_connectors()
        self.engine.finish()
        plane.close()
