"""``pw.io.sqlite`` — SQLite connector.

reference: python/pathway/io/sqlite + ``SqliteReader``
(src/connectors/data_storage.rs:1415, tracked via sqlite's
``data_version`` pragma).  Fully functional here (sqlite3 is stdlib):
streaming mode polls ``PRAGMA data_version`` + content diffing, so row
updates/deletes become retractions exactly like the Rust reader.
"""

from __future__ import annotations

import sqlite3
import time as _time
from pathlib import Path
from typing import Any

from ...internals.schema import SchemaMetaclass
from ...internals.table import Table
from .._subscribe import subscribe
from .._utils import coerce_row, input_table
from ...internals.keys import ref_scalar
from ..streaming import ConnectorSubject

__all__ = ["read", "write"]


class _SqliteSubject(ConnectorSubject):
    _shared_source = True

    def __init__(self, path, table_name, schema, mode, refresh_s, autocommit_ms):
        super().__init__(datasource_name=f"sqlite:{path}:{table_name}")
        self.path = str(path)
        self.table_name = table_name
        self.row_schema = schema
        self._mode = "static" if mode == "static" else "streaming"
        self.refresh_s = refresh_s
        self._autocommit_ms = autocommit_ms
        self._emitted: dict[Any, tuple] = {}

    def _snapshot(self) -> dict[Any, tuple]:
        con = sqlite3.connect(self.path)
        con.row_factory = sqlite3.Row
        try:
            cols = list(self.row_schema.column_names())
            pk = self._primary_key or []
            rows = con.execute(
                f'SELECT rowid AS _rowid_, * FROM "{self.table_name}"'
            ).fetchall()
            out = {}
            for r in rows:
                rec = coerce_row(self.row_schema, dict(r))
                if pk:
                    key = ref_scalar(*[rec[c] for c in pk])
                else:
                    key = ref_scalar("__sqlite__", self.table_name, r["_rowid_"])
                out[key] = tuple(rec.get(n) for n in cols)
            return out
        finally:
            con.close()

    def _sync(self) -> bool:
        current = self._snapshot()
        changed = False
        for key, values in list(self._emitted.items()):
            if key not in current:
                self._remove(key, values)
                del self._emitted[key]
                changed = True
        for key, values in current.items():
            old = self._emitted.get(key)
            if old == values:
                continue
            if old is not None:
                self._remove(key, old)
            self._add_inner(key, values)
            self._emitted[key] = values
            changed = True
        if changed:
            self.commit()
        return changed

    def _data_version(self) -> int:
        con = sqlite3.connect(self.path)
        try:
            return con.execute("PRAGMA data_version").fetchone()[0]
        finally:
            con.close()

    def run(self) -> None:
        self._sync()
        if self._mode == "static":
            return
        last_version = self._data_version()
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            version = self._data_version()
            # data_version only changes for *other* connections' writes;
            # re-diff content either way to also catch same-process writes
            self._sync()
            last_version = version

    def current_offsets(self):
        return dict(self._emitted)

    def seek(self, offsets) -> None:
        if offsets:
            self._emitted = dict(offsets)


def read(
    path: str | Path,
    table_name: str,
    schema: SchemaMetaclass,
    *,
    mode: str = "streaming",
    refresh_interval: float = 1.0,
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
) -> Table:
    subject = _SqliteSubject(
        path, table_name, schema, mode, refresh_interval, autocommit_duration_ms
    )
    subject.persistent_id = persistent_id
    subject._configure(schema, schema.primary_key_columns())
    return input_table(schema, subject=subject)


def write(table: Table, path: str | Path, table_name: str) -> None:
    """Maintain a sqlite table mirroring the stream (insert on +1 diff,
    delete on -1; reference pattern of PsqlWriter's snapshot mode)."""
    names = table.column_names()
    con = sqlite3.connect(str(path), check_same_thread=False)
    col_defs = ", ".join(f'"{n}"' for n in names)
    con.execute(
        f'CREATE TABLE IF NOT EXISTS "{table_name}" ({col_defs})'
    )
    con.commit()

    placeholders = ", ".join("?" for _ in names)
    where = " AND ".join(f'"{n}" IS ?' for n in names)

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        vals = [_sql_value(row[n]) for n in names]
        if is_addition:
            con.execute(
                f'INSERT INTO "{table_name}" VALUES ({placeholders})', vals
            )
        else:
            cur = con.execute(
                f'SELECT rowid FROM "{table_name}" WHERE {where} LIMIT 1', vals
            ).fetchone()
            if cur is not None:
                con.execute(
                    f'DELETE FROM "{table_name}" WHERE rowid = ?', (cur[0],)
                )
        con.commit()

    def _sql_value(v):
        from ...internals.value import Json, Pointer

        if isinstance(v, Json):
            return v.to_string()
        if isinstance(v, Pointer):
            return str(v)
        return v

    subscribe(
        table, on_change=on_change, on_end=con.close, name=f"sqlite:{table_name}"
    )
