"""``pw.io.bigquery`` — BigQuery sink
(reference: python/pathway/io/bigquery).  Needs ``google-cloud-bigquery``.
"""

from __future__ import annotations

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write"]


def write(table: Table, dataset_name: str, table_name: str, service_user_credentials_file: str | None = None, **kwargs) -> None:
    from google.cloud import bigquery  # optional dependency

    if service_user_credentials_file is not None:
        client = bigquery.Client.from_service_account_json(service_user_credentials_file)
    else:
        client = bigquery.Client()
    names = table.column_names()
    target = f"{dataset_name}.{table_name}"

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        doc = {n: row[n] for n in names}
        doc["time"] = time
        doc["diff"] = 1 if is_addition else -1
        errors = client.insert_rows_json(target, [doc])
        if errors:
            raise RuntimeError(f"bigquery insert failed: {errors}")

    subscribe(table, on_change=on_change, name=f"bq:{target}")
