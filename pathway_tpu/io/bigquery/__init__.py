"""``pw.io.bigquery`` — BigQuery sink
(reference: python/pathway/io/bigquery over the buffered Rust writer,
src/connectors/data_storage.rs:1080+).  Needs ``google-cloud-bigquery``.
"""

from __future__ import annotations

from typing import Any

from ...internals.table import Table
from .._buffered import buffered_subscribe

__all__ = ["write"]


def write(
    table: Table,
    dataset_name: str,
    table_name: str,
    service_user_credentials_file: str | None = None,
    *,
    max_batch_size: int = 500,  # BigQuery's insert_rows_json soft limit
    max_retries: int = 3,
    client: Any = None,
    **kwargs,
) -> None:
    if client is None:
        from google.cloud import bigquery  # optional dependency

        if service_user_credentials_file is not None:
            client = bigquery.Client.from_service_account_json(
                service_user_credentials_file
            )
        else:
            client = bigquery.Client()
    target = f"{dataset_name}.{table_name}"

    def flush_batch(batch: list[dict]) -> None:
        errors = client.insert_rows_json(target, batch)
        if errors:
            raise RuntimeError(f"bigquery insert failed: {errors}")

    buffered_subscribe(
        table,
        flush_batch,
        name=f"bq:{target}",
        max_batch=max_batch_size,
        max_retries=max_retries,
    )
