"""``pw.io.fs`` — filesystem connector.

reference: python/pathway/io/fs/__init__.py (read:369, write) backed by the
Rust posix-like scanner (src/connectors/scanner/filesystem.rs:142,
posix_like.rs:279 — glob matching, dir polling, per-file metadata) and the
dsv/json formats (src/connectors/data_format.rs).

Here the scanner is a ``ConnectorSubject``: in streaming mode it polls the
path, diffing the (path → mtime,size) snapshot; a changed file retracts
every row it previously produced and re-emits — the upsert/delete diff
mechanism the HBM index consumes downstream (SURVEY §3.4).
"""

from __future__ import annotations

import csv as _csv
import glob as _glob
import json as _json
import os
import time as _time
from pathlib import Path
from typing import Any, Iterable

from ...internals.schema import SchemaMetaclass, schema_from_types
from ...internals.table import Table
from ...internals.value import Json
from .._utils import coerce_row, input_table, with_metadata_schema
from ..streaming import ConnectorSubject, next_autogen_key
from ...internals.keys import ref_scalar

__all__ = ["read", "write"]


def _file_metadata(path: str) -> dict:
    st = os.stat(path)
    return {
        "path": os.fspath(path),
        "size": st.st_size,
        "modified_at": int(st.st_mtime),
        "seen_at": int(_time.time()),
    }


class _FsSubject(ConnectorSubject):
    """Scans ``path`` (file, dir, or glob), emitting one row per file
    (binary/plaintext) or per record (csv/json/plaintext-by-line)."""

    # every process sees the same directory: multi-process runs keep only
    # each process's owned shard of keys (io/streaming.py ownership filter)
    _shared_source = True

    def __init__(
        self,
        path: str | Path,
        fmt: str,
        schema: SchemaMetaclass,
        mode: str,
        with_metadata: bool,
        object_pattern: str,
        refresh_s: float,
        autocommit_ms: int | None,
        csv_settings=None,
        append_only: bool = False,
    ):
        super().__init__(datasource_name=f"fs:{path}")
        self.path = os.fspath(path)
        self.fmt = fmt
        self.schema_for_rows = schema
        self._mode = "static" if mode == "static" else "streaming"
        self.with_metadata = with_metadata
        self.object_pattern = object_pattern
        self.refresh_s = refresh_s
        self._autocommit_ms = autocommit_ms
        self.csv_settings = csv_settings
        #: opt-in log-tailing mode: grown files emit only new lines
        self.append_only = append_only
        self._consumed: dict[str, int] = {}
        self._overlaps: dict[str, bytes] = {}
        self._line_counts: dict[str, int] = {}
        # path -> (mtime, size, [row keys])
        self._seen: dict[str, tuple[float, int, list]] = {}

    # offsets = the whole scan state: restoring it suppresses re-emission of
    # unchanged files and lets later modifications retract the exact rows the
    # pre-restart run produced (reference: OffsetAntichain FilePosition
    # offsets + seek, src/connectors/offset.rs / data_storage.rs:398)
    def current_offsets(self):
        return dict(self._seen)

    def seek(self, offsets) -> None:
        if offsets:
            self._seen = dict(offsets)

    def _list_files(self) -> list[str]:
        p = self.path
        if os.path.isfile(p):
            return [p]
        if os.path.isdir(p):
            pattern = os.path.join(p, "**", self.object_pattern)
            return sorted(
                f for f in _glob.glob(pattern, recursive=True) if os.path.isfile(f)
            )
        return sorted(f for f in _glob.glob(p) if os.path.isfile(f))

    def _rows_of_file(self, path: str) -> Iterable[tuple[Any, dict]]:
        """Yield (key_material, column dict) per record."""
        meta = _file_metadata(path) if self.with_metadata else None

        def attach(d: dict) -> dict:
            if meta is not None:
                d["_metadata"] = Json(meta)
            return d

        if self.fmt == "binary":
            with open(path, "rb") as f:
                yield (path,), attach({"data": f.read()})
        elif self.fmt in ("plaintext_by_file",):
            with open(path, "r", errors="replace") as f:
                yield (path,), attach({"data": f.read()})
        elif self.fmt == "plaintext":
            with open(path, "r", errors="replace") as f:
                for i, line in enumerate(f):
                    yield (path, i), attach({"data": line.rstrip("\n")})
        elif self.fmt == "csv":
            settings = self.csv_settings
            reader_kwargs = settings.reader_kwargs() if settings else {}
            comment = settings.comment_character if settings else None
            with open(path, newline="") as f:
                lines = (
                    (ln for ln in f if not ln.lstrip().startswith(comment))
                    if comment
                    else f
                )
                for i, rec in enumerate(_csv.DictReader(lines, **reader_kwargs)):
                    yield (path, i), attach(coerce_row(self.schema_for_rows, rec))
        elif self.fmt in ("json", "jsonlines"):
            with open(path) as f:
                for i, line in enumerate(f):
                    line = line.strip()
                    if not line:
                        continue
                    rec = _json.loads(line)
                    yield (path, i), attach(coerce_row(self.schema_for_rows, rec))
        else:
            raise ValueError(f"unknown format {self.fmt!r}")

    def _emit_file(self, path: str) -> list:
        keys = []
        pk_cols = self._primary_key
        for key_material, row in self._rows_of_file(path):
            values = tuple(row.get(n) for n in self._column_names)
            if pk_cols:
                key = ref_scalar(*[row.get(c) for c in pk_cols])
            else:
                key = ref_scalar("__fs__", *key_material)
            self._add_inner(key, values)
            keys.append((key, values))
        return keys

    def _scan_once(self) -> bool:
        changed = False
        current = {}
        for path in self._list_files():
            try:
                st = os.stat(path)
            except OSError:
                continue
            current[path] = (st.st_mtime, st.st_size)
        # deletions
        for path in list(self._seen):
            if path not in current:
                _, _, keys = self._seen.pop(path)
                self._append_state_clear(path)
                for key, values in keys:
                    self._remove(key, values)
                changed = True
        # additions / modifications
        for path, (mtime, size) in current.items():
            old = self._seen.get(path)
            if old is not None and (old[0], old[1]) == (mtime, size):
                continue
            if self.append_only and self.fmt in (
                "plaintext", "json", "jsonlines"
            ):
                changed |= self._scan_append_mode(path, old, mtime, size)
                continue
            if old is not None:
                for key, values in old[2]:
                    self._remove(key, values)
            try:
                keys = self._emit_file(path)
            except OSError:
                continue
            self._seen[path] = (mtime, size, keys)
            changed = True
        if changed:
            self.commit()
        return changed

    # ---- append-only tailing (opt-in log mode) --------------------------

    #: bytes of pre-growth tail re-read to confirm a pure append
    _APPEND_OVERLAP = 64

    def _append_state_clear(self, path: str) -> None:
        self._consumed.pop(path, None)
        self._overlaps.pop(path, None)
        self._line_counts.pop(path, None)

    def _emit_record(self, path, line_idx, row, keys, meta) -> None:
        """One row into the stream — the single emit contract shared by
        the append reader (the full-read path keeps _emit_file)."""
        if meta is not None:
            row["_metadata"] = Json(meta)
        values = tuple(row.get(n) for n in self._column_names)
        if self._primary_key:
            key = ref_scalar(*[row.get(c) for c in self._primary_key])
        else:
            key = ref_scalar("__fs__", path, line_idx)
        self._add_inner(key, values)
        keys.append((key, values))

    def _scan_append_mode(self, path, old, mtime, size) -> bool:
        """Grown files consume only their new complete lines; anything
        else (first sight, shrink/rotation, overlap mismatch, state lost
        in a persistence restore) retracts and re-reads from offset 0
        through the same byte reader, so both paths emit identical
        values (CRLF handling included)."""
        grown = (
            old is not None
            and size >= old[1]
            and path in self._consumed  # restore drops append state
        )
        if grown:
            keys = old[2]
            try:
                if self._read_line_region(path, keys):
                    self._seen[path] = (mtime, size, keys)
                    return True
            except OSError:
                return False
        # full reset + re-read
        if old is not None:
            for key, values in old[2]:
                self._remove(key, values)
        self._append_state_clear(path)
        keys: list = []
        try:
            self._read_line_region(path, keys)
        except OSError:
            return old is not None
        self._seen[path] = (mtime, size, keys)
        return True

    def _read_line_region(self, path: str, keys: list) -> bool:
        """Consume complete lines from ``_consumed[path]`` (0 when fresh),
        emitting rows keyed by file line index; updates consumed offset,
        line count, and the overlap snapshot.  Returns False when the
        pre-growth overlap no longer matches (not a pure append).

        The ``tail -F`` trade-off applies: an in-place edit strictly
        before the overlap window is only caught by the default mode.
        Partial trailing lines are held until their newline arrives
        (writers may flush mid-line)."""
        consumed = self._consumed.get(path, 0)
        line_idx = self._line_counts.get(path, 0)
        with open(path, "rb") as f:
            lap = min(self._APPEND_OVERLAP, consumed)
            overlap = b""
            if lap:
                f.seek(consumed - lap)
                overlap = f.read(lap)
                stored = self._overlaps.get(path)
                if stored is not None and overlap != stored[-lap:]:
                    return False
            new_data = f.read()
        cut = new_data.rfind(b"\n")
        if cut < 0:
            return True  # grew, but no complete new line yet
        block = new_data[: cut + 1]
        meta = _file_metadata(path) if self.with_metadata else None
        for line in block.decode("utf-8", errors="replace").split("\n")[:-1]:
            if line.endswith("\r"):
                # text-mode universal newlines give the full-read path
                # \r\n -> \n; match it byte-side
                line = line[:-1]
            if self.fmt in ("json", "jsonlines"):
                if line.strip():
                    self._emit_record(
                        path, line_idx,
                        coerce_row(self.schema_for_rows, _json.loads(line)),
                        keys, meta,
                    )
            else:  # plaintext
                self._emit_record(path, line_idx, {"data": line}, keys, meta)
            line_idx += 1
        self._consumed[path] = consumed + cut + 1
        self._line_counts[path] = line_idx
        self._overlaps[path] = (overlap + block)[-self._APPEND_OVERLAP:]
        return True

    def run(self) -> None:
        self._scan_once()
        if self._mode == "static":
            return
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            self._scan_once()


def read(
    path: str | Path,
    *,
    format: str = "csv",
    schema: SchemaMetaclass | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    object_pattern: str = "*",
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 1.0,
    persistent_id: str | None = None,
    csv_settings=None,
    append_only: bool = False,
    **kwargs: Any,
) -> Table:
    """Read files under ``path`` (reference io/fs/__init__.py:369).

    format: "csv" | "json" (jsonlines) | "plaintext" (row per line) |
    "plaintext_by_file" | "binary".  mode: "streaming" polls for
    new/changed/deleted files; "static" reads once at build time.

    ``append_only=True`` (plaintext/jsonlines): grown files emit only
    their new complete lines instead of retract + full re-read — linear
    instead of quadratic on log-style appends.  Non-append modifications
    are detected via a tail-overlap check (``tail -F`` semantics: an
    in-place edit strictly before the overlap window needs the default
    mode) and fall back to the full re-read.
    """
    if append_only and format not in ("plaintext", "json", "jsonlines"):
        raise ValueError(
            "append_only=True supports line formats (plaintext/jsonlines), "
            f"not {format!r}"
        )
    if format in ("binary",):
        schema = schema_from_types(data=bytes)
    elif format in ("plaintext", "plaintext_by_file"):
        schema = schema_from_types(data=str)
    elif schema is None:
        raise ValueError(f"format {format!r} requires a schema")
    row_schema = schema
    out_schema = with_metadata_schema(schema) if with_metadata else schema
    subject = _FsSubject(
        path,
        format,
        row_schema,
        mode,
        with_metadata,
        object_pattern,
        refresh_interval,
        autocommit_duration_ms,
        csv_settings=csv_settings,
        append_only=append_only,
    )
    subject.persistent_id = persistent_id
    subject._configure(out_schema, schema.primary_key_columns())
    return input_table(out_schema, subject=subject)


def write(table: Table, filename: str | Path, *, format: str = "csv") -> None:
    """Write the table's update stream to a file (reference FileWriter,
    src/connectors/data_storage.rs:649 + dsv/json formatters)."""
    if format == "csv":
        from .. import csv as _csv_mod

        _csv_mod.write(table, filename)
    elif format in ("json", "jsonlines"):
        from .. import jsonlines as _jl

        _jl.write(table, filename)
    else:
        raise ValueError(f"unknown format {format!r}")
