"""``pw.io.deltalake`` — Delta Lake connector.

reference: python/pathway/io/deltalake over the Rust
``DeltaTableWriter``/``DeltaTableReader`` (src/connectors/
data_storage.rs:1621/1924, DeltaVersion offsets).  Needs ``deltalake``.
"""

from __future__ import annotations

import time as _time
from typing import Any

from ...internals.schema import SchemaMetaclass
from ...internals.table import Table
from .._subscribe import subscribe
from .._utils import coerce_row, input_table
from ...internals.keys import ref_scalar
from ..streaming import ConnectorSubject, next_autogen_key

__all__ = ["read", "write"]


class _DeltaSubject(ConnectorSubject):
    _shared_source = True

    def __init__(self, uri, schema, mode, refresh_s, autocommit_ms):
        super().__init__(datasource_name=f"delta:{uri}")
        self.uri = uri
        self.row_schema = schema
        self._mode = "static" if mode == "static" else "streaming"
        self.refresh_s = refresh_s
        self._autocommit_ms = autocommit_ms
        self._version = -1

    def _load(self) -> bool:
        from deltalake import DeltaTable  # optional dependency

        dt = DeltaTable(self.uri)
        version = dt.version()
        if version == self._version:
            return False
        records = dt.to_pyarrow_table().to_pylist()
        emitted = False
        for rec in records[self._count if hasattr(self, "_count") else 0:]:
            row = coerce_row(self.row_schema, rec)
            values = tuple(row.get(n) for n in self._column_names)
            if self._primary_key:
                key = ref_scalar(*[row.get(c) for c in self._primary_key])
            else:
                key = next_autogen_key("delta")
            self._add_inner(key, values)
            emitted = True
        self._count = len(records)
        self._version = version
        if emitted:
            self.commit()
        return emitted

    def run(self) -> None:
        self._load()
        if self._mode == "static":
            return
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            self._load()

    def current_offsets(self):
        return {"version": self._version, "count": getattr(self, "_count", 0)}

    def seek(self, offsets) -> None:
        if offsets:
            self._version = offsets.get("version", -1)
            self._count = offsets.get("count", 0)


def read(uri: str, *, schema: SchemaMetaclass, mode: str = "streaming", refresh_interval: float = 5.0, autocommit_duration_ms: int | None = 1500, persistent_id: str | None = None, **kwargs: Any) -> Table:
    subject = _DeltaSubject(uri, schema, mode, refresh_interval, autocommit_duration_ms)
    subject.persistent_id = persistent_id
    subject._configure(schema, schema.primary_key_columns())
    return input_table(schema, subject=subject)


def write(table: Table, uri: str, *, min_commit_frequency: int | None = 60_000, **kwargs) -> None:
    import pyarrow as pa  # optional dependency
    from deltalake import write_deltalake  # optional dependency

    names = table.column_names()
    buffer: list[dict] = []

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        doc = {n: row[n] for n in names}
        doc["time"] = time
        doc["diff"] = 1 if is_addition else -1
        buffer.append(doc)

    def flush() -> None:
        if buffer:
            write_deltalake(uri, pa.Table.from_pylist(buffer), mode="append")
            buffer.clear()

    def on_time_end(time: int) -> None:
        flush()

    subscribe(table, on_change=on_change, on_time_end=on_time_end, on_end=flush, name=f"delta:{uri}")
