"""``pw.io.gdrive`` — Google Drive source.

reference: python/pathway/io/gdrive (401 LoC) — polls a Drive folder,
emits file contents as binary rows with metadata, detects modifications
and deletions.  Needs ``google-api-python-client`` at call time.
"""

from __future__ import annotations

import time as _time
from typing import Any

from ...internals.schema import schema_from_types
from ...internals.table import Table
from .._utils import input_table, with_metadata_schema
from ...internals.keys import ref_scalar
from ...internals.value import Json
from ..streaming import ConnectorSubject

__all__ = ["read"]


class _GDriveSubject(ConnectorSubject):
    _shared_source = True

    def __init__(self, object_id, credentials, mode, refresh_s, with_metadata, autocommit_ms):
        super().__init__(datasource_name=f"gdrive:{object_id}")
        self.object_id = object_id
        self.credentials = credentials
        self._mode = "static" if mode == "static" else "streaming"
        self.refresh_s = refresh_s
        self.with_metadata = with_metadata
        self._autocommit_ms = autocommit_ms
        self._seen: dict[str, tuple] = {}

    def _service(self):
        from googleapiclient.discovery import build  # optional dependency

        return build("drive", "v3", credentials=self.credentials)

    def _scan(self) -> None:
        service = self._service()
        query = f"'{self.object_id}' in parents and trashed = false"
        resp = service.files().list(q=query, fields="files(id, name, modifiedTime, mimeType)").execute()
        current = {f["id"]: f for f in resp.get("files", [])}
        for fid in list(self._seen):
            if fid not in current:
                stamp, key, values = self._seen.pop(fid)
                self._remove(key, values)
        for fid, meta in current.items():
            stamp = meta.get("modifiedTime")
            old = self._seen.get(fid)
            if old is not None and old[0] == stamp:
                continue
            if old is not None:
                self._remove(old[1], old[2])
            content = service.files().get_media(fileId=fid).execute()
            key = ref_scalar("__gdrive__", fid)
            row = {"data": content}
            if self.with_metadata:
                row["_metadata"] = Json(dict(meta))
            values = tuple(row.get(n) for n in self._column_names)
            self._add_inner(key, values)
            self._seen[fid] = (stamp, key, values)
        self.commit()

    def run(self) -> None:
        self._scan()
        if self._mode == "static":
            return
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            self._scan()

    def current_offsets(self):
        return dict(self._seen)

    def seek(self, offsets) -> None:
        if offsets:
            self._seen = dict(offsets)


def read(
    object_id: str,
    *,
    service_user_credentials_file: str | None = None,
    credentials: Any = None,
    mode: str = "streaming",
    refresh_interval: float = 30.0,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if credentials is None:
        from google.oauth2.service_account import Credentials  # optional dependency

        credentials = Credentials.from_service_account_file(
            service_user_credentials_file,
            scopes=["https://www.googleapis.com/auth/drive.readonly"],
        )
    schema = schema_from_types(data=bytes)
    out_schema = with_metadata_schema(schema) if with_metadata else schema
    subject = _GDriveSubject(
        object_id, credentials, mode, refresh_interval, with_metadata,
        autocommit_duration_ms,
    )
    subject.persistent_id = persistent_id
    subject._configure(out_schema, None)
    return input_table(out_schema, subject=subject)
