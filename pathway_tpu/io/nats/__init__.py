"""``pw.io.nats`` — NATS connector.

reference: python/pathway/io/nats over the Rust ``NatsReader``/``NatsWriter``
(src/connectors/data_storage.rs:2271/2345).  Needs ``nats-py`` at call time.
"""

from __future__ import annotations

import asyncio
import json as _json
from typing import Any

from ...internals.schema import SchemaMetaclass, schema_from_types
from ...internals.table import Table
from .._subscribe import subscribe
from .._utils import coerce_row, input_table, jsonable_cell
from ...internals.keys import ref_scalar
from ..streaming import ConnectorSubject, next_autogen_key

__all__ = ["read", "write"]


class _NatsSubject(ConnectorSubject):
    def __init__(self, uri, topic, fmt, schema, autocommit_ms):
        super().__init__(datasource_name=f"nats:{topic}")
        self.uri = uri
        self.topic = topic
        self.fmt = fmt
        self.row_schema = schema
        self._autocommit_ms = autocommit_ms

    def run(self) -> None:
        import nats  # optional dependency

        async def consume():
            nc = await nats.connect(self.uri)
            sub = await nc.subscribe(self.topic)
            try:
                while not self._closed.is_set():
                    try:
                        msg = await sub.next_msg(timeout=0.5)
                    except Exception:
                        continue
                    payload = msg.data
                    if self.fmt == "raw":
                        row = {"data": payload}
                    elif self.fmt == "plaintext":
                        row = {"data": payload.decode(errors="replace")}
                    else:
                        row = coerce_row(self.row_schema, _json.loads(payload))
                    values = tuple(row.get(n) for n in self._column_names)
                    if self._primary_key:
                        key = ref_scalar(*[row.get(c) for c in self._primary_key])
                    else:
                        key = next_autogen_key("nats")
                    self._add_inner(key, values)
                    self.commit()
            finally:
                await nc.close()

        asyncio.run(consume())


def read(
    uri: str,
    topic: str,
    *,
    schema: SchemaMetaclass | None = None,
    format: str = "json",
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if format == "raw":
        schema = schema_from_types(data=bytes)
    elif format == "plaintext":
        schema = schema_from_types(data=str)
    elif schema is None:
        raise ValueError(f"format {format!r} requires schema=")
    subject = _NatsSubject(uri, topic, format, schema, autocommit_duration_ms)
    subject.persistent_id = persistent_id
    subject._configure(schema, schema.primary_key_columns())
    return input_table(schema, subject=subject)


def write(table: Table, uri: str, topic: str, *, format: str = "json", **kwargs) -> None:
    import nats  # optional dependency

    names = table.column_names()
    loop = asyncio.new_event_loop()
    nc_holder: list = []

    def _ensure_nc():
        if not nc_holder:
            nc_holder.append(loop.run_until_complete(nats.connect(uri)))
        return nc_holder[0]

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        payload = {n: jsonable_cell(row[n]) for n in names}
        payload["time"] = time
        payload["diff"] = 1 if is_addition else -1
        nc = _ensure_nc()
        loop.run_until_complete(nc.publish(topic, _json.dumps(payload, default=str).encode()))

    def on_end() -> None:
        if nc_holder:
            loop.run_until_complete(nc_holder[0].close())
        loop.close()

    subscribe(table, on_change=on_change, on_end=on_end, name=f"nats:{topic}")
