"""``pw.io.python`` — custom Python connector subjects.

reference: python/pathway/io/python/__init__.py (``ConnectorSubject``:49,
``read``:432).
"""

from __future__ import annotations

from typing import Any

from ...internals.schema import SchemaMetaclass
from ...internals.table import Table
from .._utils import input_table
from ..streaming import ConnectorSubject

__all__ = ["ConnectorSubject", "read"]


def read(
    subject: ConnectorSubject,
    *,
    schema: SchemaMetaclass,
    autocommit_duration_ms: int | None = 1500,
    primary_key: list[str] | None = None,
    **kwargs: Any,
) -> Table:
    """Read from a custom ``ConnectorSubject`` (reference
    io/python/__init__.py:432).  The subject runs on its own thread under
    ``pw.run``; rows become visible at each ``commit()``."""
    pk = primary_key or schema.primary_key_columns()
    subject._configure(schema, pk)
    subject._autocommit_ms = autocommit_duration_ms
    return input_table(schema, subject=subject)
