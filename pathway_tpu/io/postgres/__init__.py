"""``pw.io.postgres`` — PostgreSQL sink.

reference: python/pathway/io/postgres over the Rust ``PsqlWriter``
(src/connectors/data_storage.rs:1080) — ``write`` appends the diff stream
with time/diff columns, ``write_snapshot`` maintains the latest row per
primary key.  Rows buffer through the shared ``io/_buffered.py`` sink (as
the ES/BigQuery sinks do) and flush with ``executemany`` at every commit
tick or once ``max_batch_size`` rows accumulate — not one round trip per
row.  Needs ``psycopg2`` (or psycopg) at call time; pass ``connection=``
to inject one (tests, pools).
"""

from __future__ import annotations

from typing import Any

from ...internals.table import Table
from .._buffered import buffered_subscribe

__all__ = ["write", "write_snapshot"]

_DEFAULT_BATCH = 512


def _connect(postgres_settings: dict):
    try:
        import psycopg2 as pg  # optional dependency
    except ImportError:
        import psycopg as pg  # optional dependency (v3)
    return pg.connect(**postgres_settings)


def _flush_statement_runs(con, batch: list[dict]) -> None:
    """executemany per run of consecutive identical statements, preserving
    the callback order (an upsert and the delete that follows it must not
    be reordered across the batch).  The whole batch is ONE transaction:
    the buffered sink retries a failed flush from the top, so a partial
    commit would duplicate the already-landed rows — rollback makes the
    retry all-or-nothing."""
    cur = con.cursor()
    try:
        run_sql: str | None = None
        run_params: list[list] = []
        for doc in batch:
            if doc["sql"] != run_sql and run_params:
                cur.executemany(run_sql, run_params)
                run_params = []
            run_sql = doc["sql"]
            run_params.append(doc["params"])
        if run_params:
            cur.executemany(run_sql, run_params)
    except Exception:
        con.rollback()
        raise
    else:
        con.commit()
    finally:
        cur.close()


def write(
    table: Table,
    postgres_settings: dict,
    table_name: str,
    *,
    max_batch_size: int | None = None,
    connection: Any = None,
) -> None:
    con = connection if connection is not None else _connect(postgres_settings)
    con.autocommit = False  # one transaction per flushed batch
    names = table.column_names()
    cols = ", ".join(names + ["time", "diff"])
    ph = ", ".join(["%s"] * (len(names) + 2))
    insert_sql = f"INSERT INTO {table_name} ({cols}) VALUES ({ph})"

    def doc_fn(key, row: dict, time: int, is_addition: bool) -> dict:
        return {
            "sql": insert_sql,
            "params": [row[n] for n in names] + [time, 1 if is_addition else -1],
        }

    buffered_subscribe(
        table,
        lambda batch: _flush_statement_runs(con, batch),
        name=f"psql:{table_name}",
        max_batch=max_batch_size or _DEFAULT_BATCH,
        on_close=con.close,
        doc_fn=doc_fn,
    )


def write_snapshot(
    table: Table,
    postgres_settings: dict,
    table_name: str,
    primary_key: list[str],
    *,
    max_batch_size: int | None = None,
    connection: Any = None,
) -> None:
    con = connection if connection is not None else _connect(postgres_settings)
    con.autocommit = False  # one transaction per flushed batch
    names = table.column_names()
    cols = ", ".join(names)
    ph = ", ".join(["%s"] * len(names))
    conflict = ", ".join(primary_key)
    updates = ", ".join(f"{n} = EXCLUDED.{n}" for n in names if n not in primary_key)
    where = " AND ".join(f"{k} = %s" for k in primary_key)
    upsert_sql = (
        f"INSERT INTO {table_name} ({cols}) VALUES ({ph}) "
        f"ON CONFLICT ({conflict}) DO UPDATE SET {updates}"
    )
    delete_sql = f"DELETE FROM {table_name} WHERE {where}"

    def doc_fn(key, row: dict, time: int, is_addition: bool) -> dict:
        if is_addition:
            return {"sql": upsert_sql, "params": [row[n] for n in names]}
        return {"sql": delete_sql, "params": [row[k] for k in primary_key]}

    buffered_subscribe(
        table,
        lambda batch: _flush_statement_runs(con, batch),
        name=f"psql:{table_name}",
        max_batch=max_batch_size or _DEFAULT_BATCH,
        on_close=con.close,
        doc_fn=doc_fn,
    )
