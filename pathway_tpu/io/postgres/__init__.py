"""``pw.io.postgres`` — PostgreSQL sink.

reference: python/pathway/io/postgres over the Rust ``PsqlWriter``
(src/connectors/data_storage.rs:1080) — ``write`` appends the diff stream
with time/diff columns, ``write_snapshot`` maintains the latest row per
primary key.  Needs ``psycopg2`` (or psycopg) at call time.
"""

from __future__ import annotations

from typing import Any

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write", "write_snapshot"]


def _connect(postgres_settings: dict):
    try:
        import psycopg2 as pg  # optional dependency
    except ImportError:
        import psycopg as pg  # optional dependency (v3)
    return pg.connect(**postgres_settings)


def write(table: Table, postgres_settings: dict, table_name: str, *, max_batch_size: int | None = None) -> None:
    con = _connect(postgres_settings)
    con.autocommit = True
    names = table.column_names()
    cols = ", ".join(names + ["time", "diff"])
    ph = ", ".join(["%s"] * (len(names) + 2))

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        with con.cursor() as cur:
            cur.execute(
                f"INSERT INTO {table_name} ({cols}) VALUES ({ph})",
                [row[n] for n in names] + [time, 1 if is_addition else -1],
            )

    subscribe(table, on_change=on_change, on_end=con.close, name=f"psql:{table_name}")


def write_snapshot(table: Table, postgres_settings: dict, table_name: str, primary_key: list[str], *, max_batch_size: int | None = None) -> None:
    con = _connect(postgres_settings)
    con.autocommit = True
    names = table.column_names()
    cols = ", ".join(names)
    ph = ", ".join(["%s"] * len(names))
    conflict = ", ".join(primary_key)
    updates = ", ".join(f"{n} = EXCLUDED.{n}" for n in names if n not in primary_key)
    where = " AND ".join(f"{k} = %s" for k in primary_key)

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        with con.cursor() as cur:
            if is_addition:
                cur.execute(
                    f"INSERT INTO {table_name} ({cols}) VALUES ({ph}) "
                    f"ON CONFLICT ({conflict}) DO UPDATE SET {updates}",
                    [row[n] for n in names],
                )
            else:
                cur.execute(
                    f"DELETE FROM {table_name} WHERE {where}",
                    [row[k] for k in primary_key],
                )

    subscribe(table, on_change=on_change, on_end=con.close, name=f"psql:{table_name}")
