"""``pw.io.s3_csv`` — CSV-over-S3 shorthand (reference: python/pathway/io/s3_csv)."""

from __future__ import annotations

from ..s3 import AwsS3Settings
from ..s3 import read as _s3_read

__all__ = ["read", "AwsS3Settings"]


def read(path, *, aws_s3_settings=None, schema=None, mode="streaming", **kwargs):
    return _s3_read(
        path, aws_s3_settings=aws_s3_settings, format="csv", schema=schema,
        mode=mode, **kwargs,
    )
