"""``pw.io`` — connectors.

reference: python/pathway/io/ (29 modules).  Implemented natively here:
fs, csv, jsonlines, plaintext, python, http (REST), null, subscribe.
Long-tail service connectors (kafka, s3, …) follow the same
``ConnectorSubject`` protocol (``streaming.py``).
"""

from . import csv, fs, http, jsonlines, null, plaintext, python
from ._subscribe import subscribe
from .streaming import ConnectorSubject, StreamingDriver

__all__ = [
    "csv",
    "fs",
    "http",
    "jsonlines",
    "null",
    "plaintext",
    "python",
    "subscribe",
    "ConnectorSubject",
    "StreamingDriver",
]
