"""``pw.io`` — connectors.

reference: python/pathway/io/ (29 modules).  Zero-dependency connectors
(fs, csv, jsonlines, plaintext, python, http, sqlite, null, slack,
logstash, subscribe) are fully live; service connectors (kafka, redpanda,
debezium, postgres, elasticsearch, mongodb, nats, pubsub, bigquery,
deltalake, s3/s3_csv/minio, gdrive, airbyte, pyfilesystem) follow the same
``ConnectorSubject`` protocol and import their client library at call
time (none are baked into this image).
"""

from . import csv, fs, http, jsonlines, null, plaintext, python, sqlite
from ._subscribe import OnChangeCallback, OnFinishCallback, subscribe
from ._utils import CsvParserSettings
from .streaming import ConnectorSubject, StreamingDriver

_LAZY = {
    "kafka",
    "redpanda",
    "debezium",
    "postgres",
    "elasticsearch",
    "logstash",
    "mongodb",
    "nats",
    "pubsub",
    "bigquery",
    "deltalake",
    "s3",
    "s3_csv",
    "minio",
    "gdrive",
    "slack",
    "airbyte",
    "pyfilesystem",
}

__all__ = sorted(
    [
        "csv",
        "fs",
        "http",
        "jsonlines",
        "null",
        "plaintext",
        "python",
        "sqlite",
        "subscribe",
        "ConnectorSubject",
        "StreamingDriver",
        "CsvParserSettings",
        "OnChangeCallback",
        "OnFinishCallback",
        *_LAZY,
    ]
)


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
