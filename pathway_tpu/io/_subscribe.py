"""``pw.io.subscribe`` — Python callbacks on a table's update stream.

reference: python/pathway/io/_subscribe.py + internals/table_subscription.py
(engine hook: subscribe_table / SubscribeCallbacks, src/engine/graph.rs:548).
"""

from __future__ import annotations

from typing import Any, Callable

from ..internals.engine import OutputNode
from ..internals.graph import G
from ..internals.table import Table

__all__ = ["subscribe", "OnChangeCallback", "OnFinishCallback"]

# callback type aliases (reference: internals/table_subscription.py
# OnChangeCallback / OnFinishCallback protocols)
OnChangeCallback = Callable[..., None]
OnFinishCallback = Callable[[], None]


def subscribe(
    table: Table,
    on_change: Callable[..., None] | None = None,
    on_end: Callable[[], None] | None = None,
    on_time_end: Callable[[int], None] | None = None,
    *,
    name: str | None = None,
) -> None:
    """Invoke ``on_change(key, row: dict, time: int, is_addition: bool)``
    for every diff, ``on_time_end(time)`` at each closed timestamp, and
    ``on_end()`` when the stream finishes."""
    names = table.column_names()

    def wrapped(key, row, time, is_addition):
        if on_change is not None:
            on_change(key, dict(zip(names, row)), time, is_addition)

    node = OutputNode(
        on_change=wrapped if on_change is not None else None,
        on_time_end=on_time_end,
        on_end=on_end,
        keep_history=False,  # long-running sinks must not accumulate diffs
        name=name or "subscribe",
    )
    G.sinks.append((table, node))
