"""``pw.io.debezium`` — CDC ingestion from Debezium-formatted Kafka topics.

reference: python/pathway/io/debezium over the Rust debezium format
(src/connectors/data_format.rs: DebeziumMessageParser — envelope ``op``
c/r/u/d becomes insert / insert / retract+insert / retract diffs).
Needs ``confluent_kafka`` at call time.
"""

from __future__ import annotations

import json as _json
from typing import Any

from ...internals.schema import SchemaMetaclass
from .._utils import coerce_row, input_table
from ...internals.keys import ref_scalar
from ...internals.table import Table
from ..kafka import _KafkaSubject

__all__ = ["read"]


class _DebeziumSubject(_KafkaSubject):
    def _emit(self, payload: bytes, msg_key: bytes | None) -> None:
        envelope = _json.loads(payload)
        body = envelope.get("payload", envelope)
        op = body.get("op", "c")
        before = body.get("before")
        after = body.get("after")

        def to_entry(rec):
            row = coerce_row(self.row_schema, rec)
            values = tuple(row.get(n) for n in self._column_names)
            if self._primary_key:
                key = ref_scalar(*[row.get(c) for c in self._primary_key])
            else:
                key = ref_scalar("__dbz__", self.topic, _json.dumps(rec, sort_keys=True, default=str))
            return key, values

        if op in ("c", "r") and after is not None:
            self._add_inner(*to_entry(after))
        elif op == "u":
            if before is not None:
                self._remove(*to_entry(before))
            if after is not None:
                self._add_inner(*to_entry(after))
        elif op == "d" and before is not None:
            self._remove(*to_entry(before))


def read(
    rdkafka_settings: dict,
    topic_name: str,
    *,
    schema: SchemaMetaclass,
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    subject = _DebeziumSubject(
        rdkafka_settings, topic_name, "json", schema, autocommit_duration_ms
    )
    subject.persistent_id = persistent_id
    subject._configure(schema, schema.primary_key_columns())
    return input_table(schema, subject=subject)
