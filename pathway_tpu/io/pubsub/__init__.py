"""``pw.io.pubsub`` — Google Cloud Pub/Sub sink
(reference: python/pathway/io/pubsub).  Needs ``google-cloud-pubsub``.
"""

from __future__ import annotations

import json as _json

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write"]


def write(table: Table, publisher, project_id: str, topic_id: str) -> None:
    names = table.column_names()
    topic_path = publisher.topic_path(project_id, topic_id)

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        payload = {n: row[n] for n in names}
        payload["time"] = time
        payload["diff"] = 1 if is_addition else -1
        publisher.publish(topic_path, _json.dumps(payload, default=str).encode())

    subscribe(table, on_change=on_change, name=f"pubsub:{topic_id}")
