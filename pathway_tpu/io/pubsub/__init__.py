"""``pw.io.pubsub`` — Google Cloud Pub/Sub sink
(reference: python/pathway/io/pubsub).  Needs ``google-cloud-pubsub``.
"""

from __future__ import annotations

import json as _json

from ...internals.table import Table
from .._buffered import buffered_subscribe

__all__ = ["write"]


def write(
    table: Table,
    publisher,
    project_id: str,
    topic_id: str,
    *,
    max_batch_size: int = 256,
    max_retries: int = 3,
) -> None:
    topic_path = publisher.topic_path(project_id, topic_id)

    def flush_batch(batch: list[dict]) -> None:
        futures = [
            publisher.publish(
                topic_path, _json.dumps(doc, default=str).encode()
            )
            for doc in batch
        ]
        for f in futures:  # publish() is async — confirm the whole batch
            if hasattr(f, "result"):
                f.result(timeout=60)

    buffered_subscribe(
        table,
        flush_batch,
        name=f"pubsub:{topic_id}",
        max_batch=max_batch_size,
        max_retries=max_retries,
    )
