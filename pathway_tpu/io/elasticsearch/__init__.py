"""``pw.io.elasticsearch`` — Elasticsearch sink.

reference: python/pathway/io/elasticsearch over the Rust
``ElasticSearchWriter`` (src/connectors/data_storage.rs:1336).
Needs the ``elasticsearch`` client at call time.
"""

from __future__ import annotations

from typing import Any

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write"]


def write(table: Table, host: str, auth: Any = None, index_name: str = "pathway", **kwargs) -> None:
    from elasticsearch import Elasticsearch  # optional dependency

    client_kwargs: dict = {"hosts": [host], **kwargs}
    if auth is not None:
        client_kwargs["basic_auth"] = auth
    client = Elasticsearch(**client_kwargs)
    names = table.column_names()

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        doc = {n: row[n] for n in names}
        doc["time"] = time
        doc["diff"] = 1 if is_addition else -1
        client.index(index=index_name, document=doc)

    subscribe(table, on_change=on_change, name=f"es:{index_name}")
