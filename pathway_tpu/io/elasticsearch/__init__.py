"""``pw.io.elasticsearch`` — Elasticsearch sink.

reference: python/pathway/io/elasticsearch over the Rust
``ElasticSearchWriter`` (src/connectors/data_storage.rs:1336 — the bulk
API with buffered batches).  Needs the ``elasticsearch`` client at call
time.
"""

from __future__ import annotations

from typing import Any

from ...internals.table import Table
from .._buffered import buffered_subscribe

__all__ = ["write"]


def write(
    table: Table,
    host: str,
    auth: Any = None,
    index_name: str = "pathway",
    *,
    max_batch_size: int = 512,
    max_retries: int = 3,
    client: Any = None,
    **kwargs,
) -> None:
    if client is None:
        from elasticsearch import Elasticsearch  # optional dependency

        client_kwargs: dict = {"hosts": [host], **kwargs}
        if auth is not None:
            client_kwargs["basic_auth"] = auth
        client = Elasticsearch(**client_kwargs)

    def flush_batch(batch: list[dict]) -> None:
        # bulk API: action line + document line per row (data_storage.rs
        # ElasticSearchWriter uses the same index-action bulk layout)
        ops: list[dict] = []
        for doc in batch:
            ops.append({"index": {"_index": index_name}})
            ops.append(doc)
        resp = client.bulk(operations=ops, index=index_name)
        if isinstance(resp, dict) and resp.get("errors"):
            raise RuntimeError(f"elasticsearch bulk failed: {resp}")

    buffered_subscribe(
        table,
        flush_batch,
        name=f"es:{index_name}",
        max_batch=max_batch_size,
        max_retries=max_retries,
    )
