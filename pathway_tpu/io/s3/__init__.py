"""``pw.io.s3`` — S3/object-store source.

reference: python/pathway/io/s3 (570 LoC) over the Rust S3 scanner
(src/connectors/scanner/s3.rs) — bucket listing with prefix, per-object
parsing, polling for new objects, etag-based change detection.
Needs ``boto3`` at call time.
"""

from __future__ import annotations

import csv as _csv
import io as _io
import json as _json
import time as _time
from typing import Any

from ...internals.schema import SchemaMetaclass, schema_from_types
from ...internals.table import Table
from .._utils import coerce_row, input_table
from ...internals.keys import ref_scalar
from ..streaming import ConnectorSubject

__all__ = ["read", "AwsS3Settings"]


class AwsS3Settings:
    """reference: io/s3 AwsS3Settings"""

    def __init__(self, bucket_name: str | None = None, access_key: str | None = None,
                 secret_access_key: str | None = None, region: str | None = None,
                 endpoint: str | None = None, with_path_style: bool = False):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.region = region
        self.endpoint = endpoint
        self.with_path_style = with_path_style

    def client(self):
        import boto3  # optional dependency

        kwargs: dict = {}
        if self.access_key:
            kwargs["aws_access_key_id"] = self.access_key
        if self.secret_access_key:
            kwargs["aws_secret_access_key"] = self.secret_access_key
        if self.region:
            kwargs["region_name"] = self.region
        if self.endpoint:
            kwargs["endpoint_url"] = self.endpoint
        return boto3.client("s3", **kwargs)


class _S3Subject(ConnectorSubject):
    _shared_source = True

    def __init__(self, path, settings, fmt, schema, mode, refresh_s, autocommit_ms):
        super().__init__(datasource_name=f"s3:{path}")
        self.path = path
        self.settings = settings
        self.fmt = fmt
        self.row_schema = schema
        self._mode = "static" if mode == "static" else "streaming"
        self.refresh_s = refresh_s
        self._autocommit_ms = autocommit_ms
        self._seen: dict[str, tuple] = {}  # key -> (etag, [entries])

    def _rows_of_object(self, body: bytes, obj_key: str):
        if self.fmt == "binary":
            yield (obj_key,), {"data": body}
        elif self.fmt == "plaintext":
            for i, line in enumerate(body.decode(errors="replace").splitlines()):
                yield (obj_key, i), {"data": line}
        elif self.fmt == "csv":
            for i, rec in enumerate(_csv.DictReader(_io.StringIO(body.decode(errors="replace")))):
                yield (obj_key, i), coerce_row(self.row_schema, rec)
        elif self.fmt in ("json", "jsonlines"):
            for i, line in enumerate(body.decode(errors="replace").splitlines()):
                if line.strip():
                    yield (obj_key, i), coerce_row(self.row_schema, _json.loads(line))
        else:
            raise ValueError(f"unknown format {self.fmt!r}")

    def _scan(self) -> bool:
        client = self.settings.client()
        bucket = self.settings.bucket_name
        changed = False
        paginator = client.get_paginator("list_objects_v2")
        current = {}
        for page in paginator.paginate(Bucket=bucket, Prefix=self.path):
            for obj in page.get("Contents", []):
                current[obj["Key"]] = obj["ETag"]
        for obj_key in list(self._seen):
            if obj_key not in current:
                _, entries = self._seen.pop(obj_key)
                for key, values in entries:
                    self._remove(key, values)
                changed = True
        for obj_key, etag in current.items():
            old = self._seen.get(obj_key)
            if old is not None and old[0] == etag:
                continue
            if old is not None:
                for key, values in old[1]:
                    self._remove(key, values)
            body = client.get_object(Bucket=bucket, Key=obj_key)["Body"].read()
            entries = []
            for key_material, row in self._rows_of_object(body, obj_key):
                values = tuple(row.get(n) for n in self._column_names)
                key = ref_scalar("__s3__", bucket, *key_material)
                self._add_inner(key, values)
                entries.append((key, values))
            self._seen[obj_key] = (etag, entries)
            changed = True
        if changed:
            self.commit()
        return changed

    def run(self) -> None:
        self._scan()
        if self._mode == "static":
            return
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            self._scan()

    def current_offsets(self):
        return dict(self._seen)

    def seek(self, offsets) -> None:
        if offsets:
            self._seen = dict(offsets)


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    format: str = "csv",
    schema: SchemaMetaclass | None = None,
    mode: str = "streaming",
    refresh_interval: float = 5.0,
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if format == "binary":
        schema = schema_from_types(data=bytes)
    elif format == "plaintext":
        schema = schema_from_types(data=str)
    elif schema is None:
        raise ValueError(f"format {format!r} requires schema=")
    settings = aws_s3_settings or AwsS3Settings()
    subject = _S3Subject(path, settings, format, schema, mode, refresh_interval, autocommit_duration_ms)
    subject.persistent_id = persistent_id
    subject._configure(schema, schema.primary_key_columns())
    return input_table(schema, subject=subject)
