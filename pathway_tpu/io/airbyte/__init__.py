"""``pw.io.airbyte`` — Airbyte-sourced streams.

reference: python/pathway/io/airbyte (341 LoC + vendored
airbyte_serverless, third_party/airbyte_serverless) — runs an Airbyte
source connector (docker or pypi flavor) and ingests its record
messages with incremental STATE checkpoints.

Two execution paths here:

* ``connector_command=[...]`` — the native protocol driver
  (``_protocol.AirbyteProtocolDriver``): any argv speaking the Airbyte
  protocol on stdout (docker image, console script, python file).
  Incremental: the connector's STATE messages become the persistence
  offset frontier, passed back via ``--state`` on resume.
* ``config_file_path=`` — an ``airbyte_serverless`` Source config, when
  that package is installed (the reference's pypi flavor).
"""

from __future__ import annotations

import time as _time
from typing import Any

from ...internals.schema import schema_from_types
from ...internals.table import Table
from .._utils import input_table
from ...internals.keys import ref_scalar
from ...internals.value import Json
from ..streaming import ConnectorSubject
from ._protocol import AirbyteProtocolDriver

__all__ = ["read", "AirbyteProtocolDriver"]


class _AirbyteSubject(ConnectorSubject):
    """airbyte_serverless-source flavor (reference pypi path)."""

    def __init__(self, source, streams, mode, refresh_s, autocommit_ms):
        super().__init__(datasource_name=f"airbyte:{streams}")
        self.source = source
        self.streams = streams
        self._mode = "static" if mode == "static" else "streaming"
        self.refresh_s = refresh_s
        self._autocommit_ms = autocommit_ms
        self._counter = 0

    def _sync_once(self) -> None:
        for record in self.source.extract(self.streams):
            data = getattr(record, "record", record)
            payload = getattr(data, "data", data)
            self._counter += 1
            key = ref_scalar("__airbyte__", self._counter)
            self._add_inner(key, (Json(payload),))
        self.commit()

    def run(self) -> None:
        self._sync_once()
        if self._mode == "static":
            return
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            self._sync_once()


class _AirbyteProtocolSubject(ConnectorSubject):
    """Native protocol-driver flavor with incremental state.

    Offsets (= the persistence frontier for exactly-once resume) are the
    connector's latest STATE blob; ``seek`` restores it so a restarted
    run passes ``--state`` and re-reads only what the connector says is
    new (reference: airbyte incremental sync modes)."""

    def __init__(self, driver, streams, mode, refresh_s, autocommit_ms):
        super().__init__(datasource_name="airbyte")
        self.driver = driver
        self.streams = streams
        self._mode = "static" if mode == "static" else "streaming"
        self.refresh_s = refresh_s
        # no wall-clock autocommit: rows must become durable exactly at
        # the connector's STATE checkpoints, or a mid-sync snapshot would
        # pair them with the PREVIOUS state and the resumed connector
        # would re-emit them (duplicates)
        self._autocommit_ms = None
        self._state: Any = None
        self._catalog: dict | None = None
        self._counter = 0

    def _sync_once(self) -> None:
        if self._catalog is None:
            self._catalog = self.driver.configured_catalog(self.streams)
        emitted = False
        for kind, payload, state in self.driver.read(self._catalog, self._state):
            if kind == "record":
                self._counter += 1
                key = ref_scalar("__airbyte__", self._counter)
                self._add_inner(key, (Json(payload.get("data", payload)),))
                emitted = True
            elif kind == "state":
                self._state = state
                if emitted:
                    # commit at connector checkpoints so the offset
                    # frontier and the emitted rows advance together
                    self.commit()
                    emitted = False
        if emitted:
            self.commit()

    def run(self) -> None:
        self._sync_once()
        if self._mode == "static":
            return
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            self._sync_once()

    # persistence frontier (io/streaming.py snapshot hooks): the counter
    # rides along so resumed runs continue the key sequence instead of
    # colliding with replayed snapshot rows
    def current_offsets(self):
        return {"state": self._state, "counter": self._counter}

    def seek(self, offsets) -> None:
        if offsets:
            self._state = offsets.get("state")
            self._counter = int(offsets.get("counter", 0) or 0)


def read(
    config_file_path: str | None = None,
    streams: list[str] | None = None,
    *,
    source: Any = None,
    connector_command: list[str] | str | None = None,
    config: dict | None = None,
    execution_type: str = "local",
    env_vars: dict[str, str] | None = None,
    mode: str = "streaming",
    refresh_interval_ms: int = 60_000,
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    """Each record becomes one row with a ``data`` Json column
    (reference: io/airbyte read:107).

    Pass ``connector_command`` (argv or shell string) to drive any
    Airbyte-protocol connector natively — e.g.
    ``["docker", "run", "--rm", "-i", "airbyte/source-faker"]`` — with
    ``config=`` as its source configuration; or ``config_file_path`` for
    an installed ``airbyte_serverless`` source.
    """
    if connector_command is not None:
        if isinstance(connector_command, str):
            import shlex

            connector_command = shlex.split(connector_command)
        driver = AirbyteProtocolDriver(
            connector_command, config, env=env_vars
        )
        schema = schema_from_types(data=Json)
        subject = _AirbyteProtocolSubject(
            driver, streams, mode, refresh_interval_ms / 1000.0,
            autocommit_duration_ms,
        )
        subject.persistent_id = persistent_id
        subject._configure(schema, None)
        return input_table(schema, subject=subject)

    if source is None:
        import yaml

        from airbyte_serverless.sources import Source  # optional dependency

        with open(config_file_path) as f:
            cfg = yaml.safe_load(f)
        source = Source(**cfg.get("source", cfg))
    schema = schema_from_types(data=Json)
    subject = _AirbyteSubject(
        source, streams or [], mode, refresh_interval_ms / 1000.0,
        autocommit_duration_ms,
    )
    subject.persistent_id = persistent_id
    subject._configure(schema, None)
    return input_table(schema, subject=subject)
