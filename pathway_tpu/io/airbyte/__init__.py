"""``pw.io.airbyte`` — Airbyte-sourced streams.

reference: python/pathway/io/airbyte (341 LoC + vendored
airbyte_serverless) — runs an Airbyte source connector (docker or pypi
flavor) and ingests its record messages.  This port drives a
locally-installed ``airbyte`` pypi source package at call time; the
docker flavor needs a docker runtime and is not wired in this image.
"""

from __future__ import annotations

import time as _time
from typing import Any

from ...internals.schema import schema_from_types
from ...internals.table import Table
from .._utils import input_table
from ...internals.keys import ref_scalar
from ...internals.value import Json
from ..streaming import ConnectorSubject

__all__ = ["read"]


class _AirbyteSubject(ConnectorSubject):
    def __init__(self, source, streams, mode, refresh_s, autocommit_ms):
        super().__init__(datasource_name=f"airbyte:{streams}")
        self.source = source
        self.streams = streams
        self._mode = "static" if mode == "static" else "streaming"
        self.refresh_s = refresh_s
        self._autocommit_ms = autocommit_ms
        self._counter = 0

    def _sync_once(self) -> None:
        for record in self.source.extract(self.streams):
            data = getattr(record, "record", record)
            payload = getattr(data, "data", data)
            self._counter += 1
            key = ref_scalar("__airbyte__", self._counter)
            self._add_inner(key, (Json(payload),))
        self.commit()

    def run(self) -> None:
        self._sync_once()
        if self._mode == "static":
            return
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            self._sync_once()


def read(
    config_file_path: str | None = None,
    streams: list[str] | None = None,
    *,
    source: Any = None,
    mode: str = "streaming",
    refresh_interval_ms: int = 60_000,
    autocommit_duration_ms: int | None = 1500,
    **kwargs: Any,
) -> Table:
    """Each record becomes one row with a ``data`` Json column
    (reference: io/airbyte read)."""
    if source is None:
        import yaml

        from airbyte_serverless.sources import Source  # optional dependency

        with open(config_file_path) as f:
            config = yaml.safe_load(f)
        source = Source(**config.get("source", config))
    schema = schema_from_types(data=Json)
    subject = _AirbyteSubject(
        source, streams or [], mode, refresh_interval_ms / 1000.0,
        autocommit_duration_ms,
    )
    subject._configure(schema, None)
    return input_table(schema, subject=subject)
