"""Native Airbyte protocol driver.

reference: python/pathway/io/airbyte + vendored ``airbyte_serverless``
(third_party/airbyte_serverless/sources.py) — there the connector runs
as a docker or pypi-venv subprocess and its stdout is parsed for Airbyte
protocol messages.  Same contract here without the vendored layer: any
command speaking the `Airbyte protocol
<https://docs.airbyte.com/understanding-airbyte/airbyte-protocol>`_ on
stdout works (``docker run -i airbyte/source-faker``, a pypi console
script, a plain python file), driven through ``spec``/``discover``/
``read`` with RECORD and STATE messages, incremental state included.
"""

from __future__ import annotations

import json
import os
import subprocess
import tempfile
from typing import Any, Iterator

__all__ = ["AirbyteProtocolDriver"]


class AirbyteProtocolDriver:
    """Runs one Airbyte source connector command and speaks the protocol.

    ``command`` is the connector argv prefix, e.g.
    ``["docker", "run", "--rm", "-i", "-v", "{workdir}:/cfg", "airbyte/source-faker"]``
    or ``["python", "my_source.py"]``.  ``{workdir}`` in any argument is
    substituted with the temp dir holding config/catalog/state files (for
    docker volume mounts the in-container paths are passed to the
    connector instead via ``path_prefix``).
    """

    def __init__(
        self,
        command: list[str],
        config: dict | None = None,
        *,
        path_prefix: str | None = None,
        env: dict[str, str] | None = None,
        timeout: float | None = None,
    ) -> None:
        self.command = list(command)
        self.config = dict(config or {})
        self.path_prefix = path_prefix
        self.env = env
        self.timeout = timeout

    # -- protocol plumbing --------------------------------------------------
    def _run(self, args: list[str], workdir: str) -> Iterator[dict]:
        command = [a.replace("{workdir}", workdir) for a in self.command]
        child_env = dict(os.environ)
        if self.env:
            child_env.update(self.env)
        proc = subprocess.Popen(
            command + args,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=child_env,
            cwd=workdir,
        )
        # drain stderr concurrently: a chatty connector filling the ~64KB
        # stderr pipe while we iterate stdout would deadlock the sync
        import collections
        import threading

        err_tail: collections.deque = collections.deque(maxlen=50)

        def _drain() -> None:
            assert proc.stderr is not None
            for line in proc.stderr:
                err_tail.append(line)

        drainer = threading.Thread(target=_drain, daemon=True)
        drainer.start()
        try:
            assert proc.stdout is not None
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue  # connectors may log non-JSON noise on stdout
            proc.wait(timeout=self.timeout)
            drainer.join(timeout=5.0)
            if proc.returncode != 0:
                err = "".join(err_tail)
                raise RuntimeError(
                    f"airbyte connector {command[0]} rc={proc.returncode}: "
                    f"{err[-500:]}"
                )
        finally:
            if proc.poll() is None:
                proc.kill()

    def _path(self, workdir: str, name: str) -> str:
        """Path as seen by the connector (docker mounts remap workdir)."""
        if self.path_prefix:
            return f"{self.path_prefix.rstrip('/')}/{name}"
        return os.path.join(workdir, name)

    # -- protocol verbs -----------------------------------------------------
    def spec(self) -> dict:
        with tempfile.TemporaryDirectory() as wd:
            for msg in self._run(["spec"], wd):
                if msg.get("type") == "SPEC":
                    return msg.get("spec", {})
        return {}

    def discover(self) -> list[dict]:
        """Stream descriptors from the connector's catalog."""
        with tempfile.TemporaryDirectory() as wd:
            with open(os.path.join(wd, "config.json"), "w") as f:
                json.dump(self.config, f)
            for msg in self._run(
                ["discover", "--config", self._path(wd, "config.json")], wd
            ):
                if msg.get("type") == "CATALOG":
                    return msg.get("catalog", {}).get("streams", [])
        return []

    def configured_catalog(self, streams: list[str] | None) -> dict:
        """Configured catalog selecting ``streams`` (all when None),
        preferring incremental sync where the stream supports it
        (reference: airbyte_serverless ConfiguredCatalog defaults)."""
        available = self.discover()
        if streams:
            wanted = set(streams)
            available = [
                s for s in available if s.get("name") in wanted
            ]
            missing = wanted - {s.get("name") for s in available}
            if missing:
                raise ValueError(f"unknown airbyte streams: {sorted(missing)}")
        configured = []
        for s in available:
            modes = s.get("supported_sync_modes") or ["full_refresh"]
            sync_mode = "incremental" if "incremental" in modes else "full_refresh"
            configured.append(
                {
                    "stream": s,
                    "sync_mode": sync_mode,
                    "destination_sync_mode": "append",
                    "cursor_field": s.get("default_cursor_field") or [],
                }
            )
        return {"streams": configured}

    def read(
        self, catalog: dict, state: Any = None
    ) -> Iterator[tuple[str, dict | None, Any]]:
        """Yield ``(kind, payload, state)`` triples: kind "record" carries
        the record payload and stream name inside, kind "state" carries
        the connector's checkpoint (persisted as the offset frontier)."""
        with tempfile.TemporaryDirectory() as wd:
            with open(os.path.join(wd, "config.json"), "w") as f:
                json.dump(self.config, f)
            with open(os.path.join(wd, "catalog.json"), "w") as f:
                json.dump(catalog, f)
            args = [
                "read",
                "--config", self._path(wd, "config.json"),
                "--catalog", self._path(wd, "catalog.json"),
            ]
            if state is not None:
                with open(os.path.join(wd, "state.json"), "w") as f:
                    json.dump(state, f)
                args += ["--state", self._path(wd, "state.json")]
            for msg in self._run(args, wd):
                mtype = msg.get("type")
                if mtype == "RECORD":
                    yield ("record", msg.get("record", {}), None)
                elif mtype == "STATE":
                    yield ("state", None, msg.get("state"))
