"""Shared io helpers: dtype coercion, input-table construction.

reference: python/pathway/io/_utils.py (RawDataSchema, MetadataSchema,
construct_schema_and_data_format) — collapsed, since parsing happens in
the Python subjects here rather than in Rust data_format.rs.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..internals import dtype as dt
from ..internals.graph import Operator
from ..internals.schema import SchemaMetaclass, schema_from_types
from ..internals.table import Table
from ..internals.universe import Universe
from ..internals.value import Json, Pointer

__all__ = [
    "RawDataSchema",
    "MetadataSchema",
    "coerce_row",
    "input_table",
    "jsonable_cell",
    "jsonable_row",
    "with_metadata_schema",
]


def jsonable_cell(v: Any) -> Any:
    """JSON-safe cell conversion for sink payloads.

    :class:`Pointer` subclasses ``int``, so ``json.dumps`` would emit
    pointer cells as bare 128-bit JSON integers — a silent format change
    from the ``^HEX`` strings and unparseable for consumers that read
    JSON numbers as float64 (JS, BigQuery).  Convert explicitly before
    the encoder's int branch ever sees them (a ``default=`` hook never
    fires for int subclasses)."""
    if isinstance(v, Pointer):
        return str(v)
    if isinstance(v, (tuple, list)):
        return [jsonable_cell(x) for x in v]
    if isinstance(v, Json):
        return jsonable_cell(v.value)
    if isinstance(v, dict):
        return {k: jsonable_cell(x) for k, x in v.items()}
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    return v


def jsonable_row(row: dict) -> dict:
    return {n: jsonable_cell(v) for n, v in row.items()}

RawDataSchema = schema_from_types(data=bytes)
PlaintextDataSchema = schema_from_types(data=str)
MetadataSchema = schema_from_types(_metadata=Json)


def with_metadata_schema(schema: SchemaMetaclass) -> SchemaMetaclass:
    if "_metadata" in schema.column_names():
        return schema
    types = {n: schema[n].dtype for n in schema.column_names()}
    types["_metadata"] = Json
    return schema_from_types(**types)


def coerce_value(v: Any, dtype) -> Any:
    if v is None:
        return None
    base = dt.unoptionalize(dtype) if hasattr(dt, "unoptionalize") else dtype
    try:
        if base is dt.INT:
            return int(v)
        if base is dt.FLOAT:
            return float(v)
        if base is dt.BOOL:
            if isinstance(v, str):
                return v.strip().lower() in ("true", "1", "t", "yes")
            return bool(v)
        if base is dt.STR:
            return v if isinstance(v, str) else str(v)
        if base is dt.BYTES:
            return v if isinstance(v, bytes) else str(v).encode()
    except (TypeError, ValueError):
        return v
    return v


def coerce_row(schema: SchemaMetaclass, raw: dict) -> dict:
    out = {}
    for n in schema.column_names():
        col = schema[n]
        if n not in raw and getattr(col, "has_default_value", False):
            out[n] = col.default_value
        else:
            out[n] = coerce_value(raw.get(n), col.dtype)
    return out


def input_table(schema: SchemaMetaclass, subject=None, **params: Any) -> Table:
    """Create an input operator + table fed by ``subject``."""
    op = Operator(
        "input", [], params=dict(schema=schema, subject=subject, **params)
    )
    return Table._new(op, schema, Universe())


class CsvParserSettings:
    """CSV parser settings (reference: io/_utils.py:125 — same fields;
    consumed by ``pw.io.csv.read``/``pw.io.fs.read(format="csv")``)."""

    def __init__(
        self,
        delimiter=",",
        quote='"',
        escape=None,
        enable_double_quote_escapes=True,
        enable_quoting=True,
        comment_character=None,
    ):
        self.delimiter = delimiter
        self.quote = quote
        self.escape = escape
        self.enable_double_quote_escapes = enable_double_quote_escapes
        self.enable_quoting = enable_quoting
        self.comment_character = comment_character

    def reader_kwargs(self) -> dict:
        import csv as _csv

        kwargs = {
            "delimiter": self.delimiter,
            "quotechar": self.quote,
            "escapechar": self.escape,
            "doublequote": self.enable_double_quote_escapes,
        }
        if not self.enable_quoting:
            kwargs["quoting"] = _csv.QUOTE_NONE
        return kwargs
