"""``pw.io.slack`` — Slack alert sink
(reference: python/pathway/xpacks/io/slack ``send_alerts`` — one chat
message per added row via the Web API; urllib, no client lib needed)."""

from __future__ import annotations

import json as _json
import urllib.request

from ...internals.table import Table
from .._subscribe import subscribe
from .._utils import jsonable_row

__all__ = ["send_alerts"]


def send_alerts(alerts: Table, slack_channel_id: str, slack_token: str) -> None:
    names = alerts.column_names()

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        if not is_addition:
            return
        if len(names) == 1:
            text = str(row[names[0]])
        else:
            text = _json.dumps(jsonable_row(row), default=str)
        req = urllib.request.Request(
            "https://slack.com/api/chat.postMessage",
            data=_json.dumps({"channel": slack_channel_id, "text": text}).encode(),
            headers={
                "Content-Type": "application/json",
                "Authorization": f"Bearer {slack_token}",
            },
            method="POST",
        )
        urllib.request.urlopen(req, timeout=30).read()

    subscribe(alerts, on_change=on_change, name=f"slack:{slack_channel_id}")
