"""Shared buffered-sink runtime: batching, commit-tick flushes, retries.

reference: the Rust connector writers buffer rows and flush on batch
boundaries with bounded retry (src/connectors/data_storage.rs:1080-1395
— e.g. ``ElasticSearchWriter``/``PsqlWriter`` buffered modes;
src/connectors/mod.rs commit-tick driven flush).  The round-1 sinks
delivered one client call per diff with no retry; this module gives every
subscribe-style sink the same production behaviors the reference gets
from its buffered writers:

- rows accumulate and flush as batches (``max_batch`` rows, or at every
  closed engine timestamp — the commit tick, so delivery aligns with the
  consistency frontier);
- transient flush failures retry with exponential backoff up to
  ``max_retries`` before surfacing (at-least-once delivery);
- the stream end flushes the tail and runs the close hook.
"""

from __future__ import annotations

import time as _time
from typing import Any, Callable

from ..internals.table import Table
from ._subscribe import subscribe
from ._utils import jsonable_row

__all__ = ["BufferedSink", "buffered_subscribe"]


class BufferedSink:
    """Accumulates row documents; flushes via ``flush_batch(list[dict])``."""

    def __init__(
        self,
        flush_batch: Callable[[list[dict]], None],
        *,
        max_batch: int = 512,
        max_retries: int = 3,
        backoff_s: float = 0.5,
        on_close: Callable[[], None] | None = None,
        sleep: Callable[[float], None] = _time.sleep,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.flush_batch = flush_batch
        self.max_batch = max_batch
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.on_close = on_close
        self._sleep = sleep
        self._buffer: list[dict] = []
        #: delivery counters (surface in per-connector monitoring)
        self.rows_delivered = 0
        self.batches_delivered = 0
        self.retries = 0

    def add(self, doc: dict) -> None:
        self._buffer.append(doc)
        if len(self._buffer) >= self.max_batch:
            self.flush()

    def flush(self) -> None:
        if not self._buffer:
            return
        batch, self._buffer = self._buffer, []
        attempt = 0
        while True:
            try:
                self.flush_batch(batch)
                break
            except Exception:
                attempt += 1
                if attempt > self.max_retries:
                    # surface after exhausting retries; the batch is lost
                    # from the buffer but the exception aborts the commit,
                    # so upstream sees the failure (at-least-once, like the
                    # reference's writer error propagation)
                    raise
                self.retries += 1
                self._sleep(self.backoff_s * (2 ** (attempt - 1)))
        self.rows_delivered += len(batch)
        self.batches_delivered += 1

    def close(self) -> None:
        try:
            self.flush()
        finally:
            if self.on_close is not None:
                self.on_close()


def buffered_subscribe(
    table: Table,
    flush_batch: Callable[[list[dict]], None],
    *,
    name: str,
    max_batch: int = 512,
    max_retries: int = 3,
    backoff_s: float = 0.5,
    on_close: Callable[[], None] | None = None,
    doc_fn: Callable[[Any, dict, int, bool], dict] | None = None,
) -> BufferedSink:
    """Subscribe ``table`` through a :class:`BufferedSink`.

    Documents default to the reference JSON formatter's layout — the row's
    columns plus ``time``/``diff`` trailer fields; pass ``doc_fn`` to
    shape them differently."""
    sink = BufferedSink(
        flush_batch,
        max_batch=max_batch,
        max_retries=max_retries,
        backoff_s=backoff_s,
        on_close=on_close,
    )

    def default_doc(key, row: dict, time: int, is_addition: bool) -> dict:
        doc = jsonable_row(row)  # Pointer cells → '^HEX' strings
        doc["time"] = time
        doc["diff"] = 1 if is_addition else -1
        return doc

    make_doc = doc_fn or default_doc

    subscribe(
        table,
        on_change=lambda key, row, time, add: sink.add(
            make_doc(key, row, time, add)
        ),
        on_time_end=lambda time: sink.flush(),
        on_end=sink.close,
        name=name,
    )
    return sink
