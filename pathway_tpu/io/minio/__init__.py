"""``pw.io.minio`` — MinIO via the S3 protocol (reference: python/pathway/io/minio)."""

from __future__ import annotations

from ..s3 import AwsS3Settings
from ..s3 import read as _s3_read

__all__ = ["read", "MinIOSettings"]


class MinIOSettings:
    def __init__(self, endpoint, bucket_name, access_key, secret_access_key, *, with_path_style=True, region=None):
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.region = region

    def create_aws_settings(self) -> AwsS3Settings:
        endpoint = self.endpoint
        if not endpoint.startswith("http"):
            endpoint = "https://" + endpoint
        return AwsS3Settings(
            bucket_name=self.bucket_name,
            access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            region=self.region,
            endpoint=endpoint,
            with_path_style=self.with_path_style,
        )


def read(path, minio_settings: MinIOSettings, *, format="csv", schema=None, mode="streaming", **kwargs):
    return _s3_read(
        path, aws_s3_settings=minio_settings.create_aws_settings(),
        format=format, schema=schema, mode=mode, **kwargs,
    )
