"""``pw.io.jsonlines`` — JSON-lines read/write.

reference: python/pathway/io/jsonlines/__init__.py over the Rust json
format (src/connectors/data_format.rs).
"""

from __future__ import annotations

import json as _json
from pathlib import Path
from typing import Any

from ...internals.schema import SchemaMetaclass
from ...internals.table import Table
from .._subscribe import subscribe
from .._utils import jsonable_cell as _jsonable

__all__ = ["read", "write"]


def read(
    path: str | Path,
    *,
    schema: SchemaMetaclass,
    mode: str = "streaming",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    **kwargs: Any,
) -> Table:
    from .. import fs

    return fs.read(
        path,
        format="json",
        schema=schema,
        mode=mode,
        with_metadata=with_metadata,
        autocommit_duration_ms=autocommit_duration_ms,
        **kwargs,
    )




def write(table: Table, filename: str | Path) -> None:
    names = table.column_names()
    f = open(filename, "w")

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        obj = {n: _jsonable(row[n]) for n in names}
        obj["time"] = time
        obj["diff"] = 1 if is_addition else -1
        f.write(_json.dumps(obj) + "\n")
        f.flush()

    subscribe(table, on_change=on_change, on_end=f.close, name=f"jsonl:{filename}")
