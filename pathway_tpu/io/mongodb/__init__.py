"""``pw.io.mongodb`` — MongoDB sink.

reference: python/pathway/io/mongodb over the Rust ``MongoWriter``
(src/connectors/data_storage.rs:2232).  Needs ``pymongo`` at call time.
"""

from __future__ import annotations

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write"]


def write(table: Table, connection_string: str, database: str, collection: str, **kwargs) -> None:
    import pymongo  # optional dependency

    client = pymongo.MongoClient(connection_string)
    coll = client[database][collection]
    names = table.column_names()

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        doc = {n: row[n] for n in names}
        doc["time"] = time
        doc["diff"] = 1 if is_addition else -1
        coll.insert_one(doc)

    subscribe(table, on_change=on_change, on_end=client.close, name=f"mongo:{collection}")
