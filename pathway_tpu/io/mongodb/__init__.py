"""``pw.io.mongodb`` — MongoDB sink.

reference: python/pathway/io/mongodb over the Rust ``MongoWriter``
(src/connectors/data_storage.rs:2232 — insert_many batches).
Needs ``pymongo`` at call time.
"""

from __future__ import annotations

from typing import Any

from ...internals.table import Table
from .._buffered import buffered_subscribe

__all__ = ["write"]


def write(
    table: Table,
    connection_string: str,
    database: str,
    collection: str,
    *,
    max_batch_size: int = 512,
    max_retries: int = 3,
    client: Any = None,
    **kwargs,
) -> None:
    close = None
    if client is None:
        import pymongo  # optional dependency

        client = pymongo.MongoClient(connection_string)
        close = client.close
    coll = client[database][collection]

    buffered_subscribe(
        table,
        coll.insert_many,
        name=f"mongo:{collection}",
        max_batch=max_batch_size,
        max_retries=max_retries,
        on_close=close,
    )
