"""``pw.io.redpanda`` — Redpanda speaks the Kafka protocol; this module is
the kafka connector under the reference's alias (python/pathway/io/redpanda).
"""

from ..kafka import read, simple_read, write

__all__ = ["read", "simple_read", "write"]
