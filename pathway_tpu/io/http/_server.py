"""aiohttp REST server connector.

reference: python/pathway/io/http/_server.py — ``PathwayWebserver``:329,
``rest_connector``:624, ``RestServerSubject``:490 (requests become input
rows; responses resolved by an ``internal_subscribe`` callback setting a
per-request asyncio event, :778-806), OpenAPI docs (``EndpointDocumentation``
:126).

The aiohttp loop runs on its own thread; the engine loop (StreamingDriver)
delivers response diffs via ``pw.io.subscribe`` and wakes the waiting
handler with ``loop.call_soon_threadsafe`` — same two-plane split as the
reference (webserver thread ↔ engine workers).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Callable, Sequence

from ...internals.schema import SchemaMetaclass
from ...internals.table import Table
from ...internals.value import Json, Pointer
from .._subscribe import subscribe
from .._utils import coerce_row, input_table
from ..streaming import ConnectorSubject, next_autogen_key

__all__ = ["PathwayWebserver", "rest_connector", "EndpointDocumentation"]


class EndpointDocumentation:
    """OpenAPI metadata for one route (reference _server.py:126)."""

    def __init__(
        self,
        *,
        summary: str | None = None,
        description: str | None = None,
        tags: Sequence[str] = (),
        method_types: Sequence[str] | None = None,
    ):
        self.summary = summary
        self.description = description
        self.tags = list(tags)
        self.method_types = method_types


class PathwayWebserver:
    """Shared aiohttp server hosting any number of rest_connector routes
    (reference _server.py:329)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 8080, with_cors: bool = False):
        self.host = host
        self.port = port
        self.with_cors = with_cors
        self._loop: asyncio.AbstractEventLoop | None = None
        self._routes: list[tuple[str, Sequence[str], Callable]] = []
        self._openapi_routes: dict[str, dict] = {}
        self._started = threading.Event()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None

    def add_raw_route(
        self,
        route: str,
        methods: Sequence[str],
        handler: Callable,
        documentation: "EndpointDocumentation | None" = None,
    ) -> None:
        """Serve ``route`` with a plain aiohttp handler instead of a
        dataflow-backed rest_connector — the serving scheduler's fused
        retrieve plane uses this to answer off the admission queue
        (xpacks/llm/_scheduler.py) while other routes ride the engine."""
        self._register(route, methods, handler, documentation)

    def _register(self, route: str, methods: Sequence[str], handler, doc) -> None:
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("cannot add routes after the server started")
            self._routes.append((route, methods, handler))
            entry: dict[str, Any] = {}
            # SLO discoverability: the exact env knob names that put this
            # route under burn-rate evaluation ride the OpenAPI entry, so
            # `curl /_schema` answers "what do I export to SLO this
            # endpoint" without reading the docs
            try:
                from ...observability.slo import endpoint_env_key

                key = endpoint_env_key(route)
                slo_knobs = [
                    f"PATHWAY_SLO_{key}_P99_MS",
                    f"PATHWAY_SLO_{key}_AVAIL",
                ]
            except Exception:  # noqa: BLE001 — schema must never fail a route add
                slo_knobs = []
            for m in methods:
                entry[m.lower()] = {
                    "summary": getattr(doc, "summary", None) or route,
                    "description": getattr(doc, "description", None) or "",
                    "tags": list(getattr(doc, "tags", []) or []),
                    "responses": {"200": {"description": "OK"}},
                }
                if slo_knobs:
                    entry[m.lower()]["x-pathway-slo-knobs"] = slo_knobs
            self._openapi_routes[route] = entry

    def openapi_description_json(self) -> dict:
        return {
            "openapi": "3.0.3",
            "info": {"title": "Pathway-TPU API", "version": "1.0"},
            "paths": self._openapi_routes,
        }

    def _ensure_started(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._serve, daemon=True, name="pw-webserver"
            )
            self._thread.start()
        self._started.wait()

    def _serve(self) -> None:
        from aiohttp import web

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        @web.middleware
        async def tracing_mw(request, handler):
            """Every request gets a trace: a caller-sent W3C
            ``traceparent`` is adopted, otherwise a trace id is minted.
            The id rides back on ``x-pathway-trace-id`` and the finished
            span (plus any per-stage children the serving planes stamped)
            lands in the in-process flight recorder — retrievable from
            ``/v1/debug/traces`` with zero external infra."""
            if request.path.startswith("/v1/debug/"):
                # reading the recorder must not write to it
                return await handler(request)
            from ...internals.flight_recorder import start_request

            trace = start_request(
                f"{request.method} {request.path}",
                request.headers.get("traceparent"),
            )
            request["pw_trace"] = trace

            def observe_slo(status: int | None) -> None:
                """Feed the SLO engine for EVERY finished request —
                latency observation is independent of trace sampling,
                and the trace id becomes the histogram exemplar linking
                a burning bucket to /v1/debug/traces."""
                try:
                    from ...observability import slo

                    slo.observe_request(
                        request.path,
                        trace.duration_ms or 0.0,
                        status,
                        # exemplars must link to traces that EXIST: an
                        # unsampled request records no spans, so its id
                        # would dead-end in /v1/debug/traces
                        trace.trace_id if trace.sampled else None,
                    )
                except Exception:  # noqa: BLE001 — SLOs must never fail a request
                    pass

            try:
                resp = await handler(request)
            except web.HTTPException as exc:
                exc.headers["x-pathway-trace-id"] = trace.trace_id
                trace.finish(status=exc.status)
                observe_slo(exc.status)
                raise
            except asyncio.CancelledError:
                # client went away mid-request — no response was sent, so
                # recording a 500 would plant phantom errors in the trace
                # dump during load spikes (and the SLO engine skips it:
                # an aborted client is not a server availability event)
                trace.set_attr("cancelled", True)
                trace.finish()
                raise
            except BaseException:
                trace.finish(status=500)
                observe_slo(500)
                raise
            resp.headers["x-pathway-trace-id"] = trace.trace_id
            trace.finish(status=resp.status)
            observe_slo(resp.status)
            return resp

        #: routes a DRAINING replica keeps answering: health/metrics
        #: probes, debug surfaces, and the fleet control plane (the
        #: router needs /v1/fleet/drain acks and watermark reads from a
        #: draining member — that is how the drain completes)
        _drain_exempt = ("/v1/health", "/v1/debug/", "/_schema",
                         "/v1/fleet/", "/status")

        @web.middleware
        async def drain_guard_mw(request, handler):
            """Graceful drain: once the fleet member starts draining,
            serving endpoints answer 503 with a REAL ``Retry-After`` so
            clients back off with jitter instead of hammering, while
            requests already in flight run to completion (this guard
            only rejects NEW arrivals).  Gated on the fleet module
            already being imported — a fleet-less server never pays the
            check beyond one dict lookup."""
            import sys as _sys

            member_mod = _sys.modules.get("pathway_tpu.fleet.member")
            if (
                member_mod is not None
                and member_mod.is_draining()
                and not any(request.path.startswith(p) for p in _drain_exempt)
            ):
                retry_after = member_mod.drain_retry_after_s()
                return web.json_response(
                    {"detail": "replica is draining", "draining": True},
                    status=503,
                    headers={"Retry-After": f"{retry_after:g}"},
                )
            return await handler(request)

        @web.middleware
        async def sanitize_errors_mw(request, handler):
            """An unhandled handler exception must not leak a traceback
            body to the client: return structured JSON 500, count it, and
            log with route context (the traceback goes to the log)."""
            try:
                return await handler(request)
            except (web.HTTPException, asyncio.CancelledError):
                raise
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "unhandled REST handler error on %s %s",
                    request.method, request.path,
                )
                from ...internals.errors import register_error

                register_error(
                    f"unhandled REST handler error on "
                    f"{request.method} {request.path}",
                    kind="http",
                    operator=request.path,
                )
                body = {
                    "error": "internal server error",
                    "route": request.path,
                }
                trace = request.get("pw_trace")
                if trace is not None:
                    # the envelope carries the trace id so a 500 report
                    # can be joined to its /v1/debug/traces breakdown
                    body["trace_id"] = trace.trace_id
                return web.json_response(body, status=500)

        app = web.Application(
            middlewares=[tracing_mw, drain_guard_mw, sanitize_errors_mw]
        )
        for route, methods, handler in self._routes:
            for m in methods:
                app.router.add_route(m, route, handler)

        async def openapi_handler(_request):
            return web.json_response(self.openapi_description_json())

        app.router.add_get("/_schema", openapi_handler)

        async def health_handler(_request):
            """Liveness/readiness: engine watchdog + connector supervision
            + breaker states + error-log counters, from the process-global
            health registry.  503 while unready (warmup, stalled engine,
            leaked ingest thread); 200 when ready — ``status`` flips to
            ``"degraded"`` when a breaker is open or a connector is in
            backoff but the service still answers."""
            from ...internals.health import get_health

            snap = get_health().snapshot()
            if snap["ready"]:
                return web.json_response(snap)
            # a real Retry-After on the unready 503: restore progress is
            # measured in seconds, and RestClientBase turns the hint into
            # jittered backoff instead of a fixed-cadence hammer
            return web.json_response(
                snap, status=503, headers={"Retry-After": "1.0"}
            )

        async def debug_traces_handler(request):
            """Flight-recorder dump: ``?trace_id=`` / ``?min_ms=`` /
            ``?category=`` / ``?limit=`` filters; ``?format=perfetto``
            returns Chrome-tracing JSON openable in chrome://tracing or
            ui.perfetto.dev — per-request stage attribution with no
            collector deployed."""
            from ...internals.flight_recorder import FlightRecorder, get_recorder

            q = request.query
            try:
                min_ms = float(q["min_ms"]) if "min_ms" in q else None
                # default: the WHOLE ring (it is already bounded by
                # PATHWAY_FLIGHT_RECORDER_CAPACITY).  A sub-ring default
                # would silently truncate every read once the ring fills,
                # and truncated reads deliberately do not clear the
                # dropped-before-read watermark — the drop alarm would
                # then read permanently hot under steady load
                limit = int(q["limit"]) if "limit" in q else None
            except (TypeError, ValueError):
                return web.json_response(
                    {"detail": "min_ms/limit must be numeric"}, status=400
                )
            rec = get_recorder()
            spans = rec.spans(
                trace_id=q.get("trace_id"),
                min_duration_ms=min_ms,
                category=q.get("category"),
                limit=limit,
            )
            if q.get("format") == "perfetto":
                return web.json_response(FlightRecorder.perfetto(spans))
            return web.json_response(
                {
                    "spans": [s.to_dict() for s in spans],
                    "recorder": rec.stats(),
                }
            )

        async def debug_profile_handler(request):
            """On-demand device profiling: capture a ``?ms=`` trace
            window (``jax.profiler`` on TPU, flight-recorder Perfetto
            export elsewhere) and serve the artifact.  Single-flight —
            409 while a capture is running; 503 when
            ``PATHWAY_PROFILE_DIR=off``.  The capture sleeps through the
            window off the event loop, so concurrent serving requests
            are untouched (that is the point: profile the LIVE load)."""
            from ...observability import profiler

            import math

            try:
                ms = float(request.query.get("ms", "500"))
            except (TypeError, ValueError):
                ms = float("nan")
            if not math.isfinite(ms):
                # nan/inf parse as floats but would blow up the sleep —
                # they are the caller's mistake, not a 500
                return web.json_response(
                    {"detail": "ms must be a finite number"}, status=400
                )
            try:
                res = await asyncio.to_thread(profiler.capture, ms)
            except profiler.ProfileInFlight as exc:
                return web.json_response({"detail": str(exc)}, status=409)
            except profiler.ProfilerDisabled as exc:
                return web.json_response({"detail": str(exc)}, status=503)
            # FileResponse streams the artifact in chunks off disk — a
            # TPU trace zip can be tens of MB, and a blocking whole-file
            # read here would stall the very serving traffic being
            # profiled (content type comes from the extension:
            # .json = flight-recorder export, .zip = jax trace)
            return web.FileResponse(
                res["path"],
                headers={
                    "x-pathway-profile-kind": res["kind"],
                    "x-pathway-profile-ms": f'{res["duration_ms"]:g}',
                    "x-pathway-profile-path": res["path"],
                },
            )

        async def status_handler(_request):
            """OpenMetrics exposition for this process.  Fleet routers
            scrape it on the health-poll cadence (telemetry federation);
            rendering walks every provider under locks, so it runs off
            the event loop."""
            from ...internals.monitoring import exposition

            text = await asyncio.to_thread(exposition)
            return web.Response(text=text, content_type="text/plain")

        if not any(route == "/v1/health" for route, _, _ in self._routes):
            app.router.add_get("/v1/health", health_handler)
        if not any(route == "/status" for route, _, _ in self._routes):
            app.router.add_get("/status", status_handler)
        if not any(route == "/v1/debug/traces" for route, _, _ in self._routes):
            app.router.add_get("/v1/debug/traces", debug_traces_handler)
        if not any(route == "/v1/debug/profile" for route, _, _ in self._routes):
            app.router.add_get("/v1/debug/profile", debug_profile_handler)
            app.router.add_post("/v1/debug/profile", debug_profile_handler)
        if self.with_cors:

            @web.middleware
            async def cors_mw(request, handler):
                if request.method == "OPTIONS":
                    resp = web.Response()
                else:
                    resp = await handler(request)
                resp.headers["Access-Control-Allow-Origin"] = "*"
                resp.headers["Access-Control-Allow-Headers"] = "*"
                resp.headers["Access-Control-Allow-Methods"] = "*"
                return resp

            app.middlewares.append(cors_mw)

        runner = web.AppRunner(app)
        self._loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        self._loop.run_until_complete(site.start())
        self._started.set()
        self._loop.run_forever()


def _jsonable(v: Any) -> Any:
    if isinstance(v, Json):
        return v.value
    if isinstance(v, Pointer):
        return str(v)
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    if isinstance(v, tuple):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    return v


class RestServerSubject(ConnectorSubject):
    """Ingests HTTP requests as rows (reference _server.py:490)."""

    #: rows are in-flight HTTP requests — request-scoped, not durable
    #: state; clients retry after a restart (recovery-plane coverage)
    _ephemeral = True

    def __init__(
        self,
        webserver: PathwayWebserver,
        route: str,
        methods: Sequence[str],
        schema: SchemaMetaclass,
        delete_completed_queries: bool,
        request_validator: Callable | None = None,
        documentation: EndpointDocumentation | None = None,
    ):
        super().__init__(datasource_name=f"rest:{route}")
        self.webserver = webserver
        self.route = route
        self.methods = methods
        self.schema = schema
        self.delete_completed_queries = delete_completed_queries
        self.request_validator = request_validator
        self._awaiting: dict[Any, tuple[asyncio.Event, list]] = {}
        self._awaiting_lock = threading.Lock()
        webserver._register(route, methods, self._handle, documentation)

    def run(self) -> None:
        self.webserver._ensure_started()
        self._closed.wait()

    async def _handle(self, request):
        from aiohttp import web

        if request.method in ("POST", "PUT", "PATCH"):
            try:
                payload = await request.json()
            except (json.JSONDecodeError, UnicodeDecodeError):
                return web.json_response(
                    {"detail": "request body is not valid JSON"}, status=400
                )
        else:
            payload = dict(request.query)
        if self.request_validator is not None:
            err = self.request_validator(payload)
            if err is not None:
                return web.json_response({"detail": str(err)}, status=400)
        row = coerce_row(self.schema, payload)
        values = tuple(row.get(n) for n in self._column_names)
        key = next_autogen_key("rest")
        event = asyncio.Event()
        holder: list = []
        with self._awaiting_lock:
            self._awaiting[key] = (event, holder)
        self._add_inner(key, values)
        self.commit()
        await event.wait()
        with self._awaiting_lock:
            self._awaiting.pop(key, None)
        if self.delete_completed_queries:
            self._remove(key, values)
            self.commit()
        result = holder[0] if holder else None
        return web.json_response(_jsonable(result))

    def _resolve(self, key, result) -> None:
        """Called from the engine thread when the response row lands."""
        with self._awaiting_lock:
            slot = self._awaiting.get(key)
        if slot is None:
            return
        event, holder = slot
        holder.append(result)
        loop = self.webserver._loop
        if loop is not None:
            loop.call_soon_threadsafe(event.set)


def rest_connector(
    host: str | None = None,
    port: int | None = None,
    *,
    webserver: PathwayWebserver | None = None,
    route: str = "/",
    schema: SchemaMetaclass | None = None,
    methods: Sequence[str] = ("POST",),
    autocommit_duration_ms: int | None = 1500,
    keep_queries: bool | None = None,
    delete_completed_queries: bool = False,
    request_validator: Callable | None = None,
    documentation: EndpointDocumentation | None = None,
) -> tuple[Table, Callable[[Table], None]]:
    """HTTP endpoint as a (query table, response writer) pair
    (reference _server.py:624).

    The returned ``response_writer`` must be called with a table keyed by
    the query table's ids and holding a ``result`` column; each request
    blocks until its row arrives.
    """
    if webserver is None:
        if host is None or port is None:
            raise ValueError("provide either webserver= or host= and port=")
        webserver = PathwayWebserver(host=host, port=port)
    if schema is None:
        raise ValueError("rest_connector requires schema=")
    if keep_queries is not None:
        delete_completed_queries = not keep_queries

    subject = RestServerSubject(
        webserver,
        route,
        methods,
        schema,
        delete_completed_queries,
        request_validator,
        documentation,
    )
    subject._configure(schema, None)
    table = input_table(schema, subject=subject)

    def response_writer(response_table: Table) -> None:
        names = response_table.column_names()
        if "result" not in names:
            raise ValueError("response table must have a 'result' column")

        def on_change(key, row: dict, time: int, is_addition: bool) -> None:
            if is_addition:
                subject._resolve(key, row["result"])

        subscribe(response_table, on_change=on_change, name=f"rest_resp:{subject.route}")

    return table, response_writer
