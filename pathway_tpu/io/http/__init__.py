"""``pw.io.http`` — REST endpoints served from the dataflow.

reference: python/pathway/io/http/ (rest_connector:624, PathwayWebserver:329).
"""

from ._server import EndpointDocumentation, PathwayWebserver, rest_connector

__all__ = ["EndpointDocumentation", "PathwayWebserver", "rest_connector"]
