"""``pw.io.http`` — REST endpoints served from the dataflow.

reference: python/pathway/io/http/ (rest_connector:624, PathwayWebserver:329).
"""

from ._client import read, write
from ._server import EndpointDocumentation, PathwayWebserver, rest_connector

__all__ = [
    "EndpointDocumentation",
    "PathwayWebserver",
    "read",
    "rest_connector",
    "write",
]
