"""HTTP *client* connectors: poll an endpoint as a source, POST diffs as
a sink.

reference: python/pathway/io/http/__init__.py (``read``: streaming GET
poller; ``write``: per-row request with format="json"); urllib-based so it
works with zero extra dependencies.
"""

from __future__ import annotations

import json as _json
import time as _time
import urllib.request
from typing import Any, Callable, Sequence

from ...internals.schema import SchemaMetaclass, schema_from_types
from ...internals.table import Table
from .._subscribe import subscribe
from .._utils import coerce_row, input_table, jsonable_row
from ...internals.keys import ref_scalar
from ..streaming import ConnectorSubject, next_autogen_key

__all__ = ["read", "write"]


class _HttpPollSubject(ConnectorSubject):
    def __init__(
        self, url, schema, headers, refresh_s, mode, allow_redirects, autocommit_ms
    ):
        super().__init__(datasource_name=f"http:{url}")
        self.url = url
        self.row_schema = schema
        self.headers = headers or {}
        self.refresh_s = refresh_s
        self._mode = "static" if mode == "static" else "streaming"
        self._autocommit_ms = autocommit_ms
        self._seen: set = set()

    def _fetch_once(self) -> None:
        req = urllib.request.Request(self.url, headers=self.headers)
        with urllib.request.urlopen(req, timeout=30) as resp:
            payload = resp.read()
        try:
            records = _json.loads(payload)
        except ValueError:
            records = [{"data": payload.decode(errors="replace")}]
        if isinstance(records, dict):
            records = [records]
        for rec in records:
            if not isinstance(rec, dict):
                rec = {"data": rec}
            row = coerce_row(self.row_schema, rec)
            values = tuple(row.get(n) for n in self._column_names)
            dedup = (values,)
            if dedup in self._seen:
                continue
            self._seen.add(dedup)
            if self._primary_key:
                key = ref_scalar(*[row.get(c) for c in self._primary_key])
            else:
                key = next_autogen_key("http")
            self._add_inner(key, values)
        self.commit()

    def run(self) -> None:
        self._fetch_once()
        if self._mode == "static":
            return
        consecutive_failures = 0
        while not self._closed.is_set():
            # exponential backoff on a flapping endpoint instead of
            # hammering it at the refresh cadence; recovery resets
            wait_s = min(
                self.refresh_s * (2.0 ** consecutive_failures), 60.0
            )
            if self._closed.wait(wait_s):
                return
            try:
                self._fetch_once()
                consecutive_failures = 0
            except Exception as exc:  # noqa: BLE001 — endpoint may flap
                consecutive_failures += 1
                if consecutive_failures in (1, 5):
                    # log the first failure and the point where backoff is
                    # clearly engaged; avoid one log line per poll forever
                    from ...internals.errors import register_error

                    register_error(
                        f"http poll of {self.url} failing "
                        f"({consecutive_failures} consecutive): "
                        f"{type(exc).__name__}: {exc}",
                        kind="connector",
                        operator=self._datasource_name,
                    )
                continue


def read(
    url: str,
    *,
    schema: SchemaMetaclass | None = None,
    format: str = "json",
    mode: str = "streaming",
    refresh_interval: float = 5.0,
    headers: dict | None = None,
    allow_redirects: bool = True,
    autocommit_duration_ms: int | None = 1500,
) -> Table:
    """Poll ``url`` and emit (new) records as rows
    (reference: io/http read)."""
    if schema is None:
        schema = schema_from_types(data=str)
    subject = _HttpPollSubject(
        url, schema, headers, refresh_interval, mode, allow_redirects,
        autocommit_duration_ms,
    )
    subject._configure(schema, schema.primary_key_columns())
    return input_table(schema, subject=subject)


def write(
    table: Table,
    url: str,
    *,
    method: str = "POST",
    format: str = "json",
    headers: dict | None = None,
    request_payload_template: Callable[[dict], Any] | None = None,
) -> None:
    """POST every diff to ``url`` as JSON ``{...row, time, diff}``
    (reference: io/http write)."""
    names = table.column_names()
    send_headers = {"Content-Type": "application/json", **(headers or {})}

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        payload = jsonable_row(row)
        payload["time"] = time
        payload["diff"] = 1 if is_addition else -1
        if request_payload_template is not None:
            payload = request_payload_template(payload)
        data = _json.dumps(payload, default=str).encode()
        req = urllib.request.Request(
            url, data=data, headers=send_headers, method=method
        )
        urllib.request.urlopen(req, timeout=30).read()

    subscribe(table, on_change=on_change, name=f"http_write:{url}")
