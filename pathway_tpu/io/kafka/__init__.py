"""``pw.io.kafka`` — Kafka connector.

reference: python/pathway/io/kafka (686 LoC) over the Rust
``KafkaReader``/``KafkaWriter`` (src/connectors/data_storage.rs:692/1258)
with ``OffsetAntichain`` Kafka offsets for exactly-once resume.

Needs ``confluent_kafka`` (imported at call time — not baked into this
image; the module is fully wired so it works where the client exists).
"""

from __future__ import annotations

import json as _json
from typing import Any, Iterable

from ...internals.schema import SchemaMetaclass, schema_from_types
from ...internals.table import Table
from .._subscribe import subscribe
from .._utils import coerce_row, input_table, jsonable_cell
from ...internals.keys import ref_scalar
from ..streaming import ConnectorSubject, next_autogen_key

__all__ = ["read", "simple_read", "write"]


class _KafkaSubject(ConnectorSubject):
    """Reader thread driving a confluent_kafka Consumer; per-partition
    offsets are the persistence frontier (reference OffsetAntichain
    KafkaOffset, src/connectors/offset.rs)."""

    def __init__(self, rdkafka_settings, topic, fmt, schema, autocommit_ms):
        super().__init__(datasource_name=f"kafka:{topic}")
        self.settings = dict(rdkafka_settings)
        self.topic = topic
        self.fmt = fmt
        self.row_schema = schema
        self._autocommit_ms = autocommit_ms
        self._offsets: dict[int, int] = {}

    def _emit(self, payload: bytes, msg_key: bytes | None) -> None:
        if self.fmt == "raw":
            row = {"data": payload}
        elif self.fmt == "plaintext":
            row = {"data": payload.decode(errors="replace")}
        else:  # json
            row = coerce_row(self.row_schema, _json.loads(payload))
        values = tuple(row.get(n) for n in self._column_names)
        if self._primary_key:
            key = ref_scalar(*[row.get(c) for c in self._primary_key])
        elif msg_key:
            key = ref_scalar("__kafka__", self.topic, msg_key)
        else:
            key = next_autogen_key("kafka")
        self._add_inner(key, values)

    def run(self) -> None:
        from confluent_kafka import Consumer, TopicPartition  # optional dependency

        consumer = Consumer(self.settings)

        def on_assign(cons, partitions):
            if self._offsets:
                for p in partitions:
                    if p.partition in self._offsets:
                        p.offset = self._offsets[p.partition] + 1
                cons.assign(partitions)

        consumer.subscribe([self.topic], on_assign=on_assign)
        try:
            while not self._closed.is_set():
                msg = consumer.poll(0.5)
                if msg is None or msg.error():
                    continue
                self._emit(msg.value(), msg.key())
                self._offsets[msg.partition()] = msg.offset()
                self.commit()
        finally:
            consumer.close()

    def current_offsets(self):
        return dict(self._offsets)

    def seek(self, offsets) -> None:
        if offsets:
            self._offsets = dict(offsets)


def read(
    rdkafka_settings: dict,
    topic: str | Iterable[str] | None = None,
    *,
    schema: SchemaMetaclass | None = None,
    format: str = "raw",
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    """reference: io/kafka read"""
    if isinstance(topic, (list, tuple)):
        topic = topic[0]
    if format in ("raw",):
        schema = schema_from_types(data=bytes)
    elif format == "plaintext":
        schema = schema_from_types(data=str)
    elif schema is None:
        raise ValueError(f"format {format!r} requires schema=")
    subject = _KafkaSubject(
        rdkafka_settings, topic, format, schema, autocommit_duration_ms
    )
    subject.persistent_id = persistent_id
    subject._configure(schema, schema.primary_key_columns())
    return input_table(schema, subject=subject)


def simple_read(
    server: str, topic: str, *, read_only_new: bool = False, **kwargs
) -> Table:
    """reference: io/kafka simple_read — minimal consumer settings."""
    settings = {
        "bootstrap.servers": server,
        "group.id": f"pathway-reader-{topic}",
        "session.timeout.ms": "6000",
        "auto.offset.reset": "latest" if read_only_new else "earliest",
    }
    return read(settings, topic, **kwargs)


def write(
    table: Table,
    rdkafka_settings: dict,
    topic_name: str,
    *,
    format: str = "json",
    delivery_timeout_s: float = 30.0,
    **kwargs: Any,
) -> None:
    """reference: io/kafka write — one JSON message per diff with
    time/diff trailer fields (the Rust json formatter's layout)."""
    from confluent_kafka import Producer  # optional dependency

    producer = Producer(dict(rdkafka_settings))
    names = table.column_names()

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        payload = {n: jsonable_cell(row[n]) for n in names}
        payload["time"] = time
        payload["diff"] = 1 if is_addition else -1
        producer.produce(
            topic_name, _json.dumps(payload, default=str).encode(), key=str(key).encode()
        )
        producer.poll(0)

    def on_end() -> None:
        producer.flush(delivery_timeout_s)

    subscribe(table, on_change=on_change, on_end=on_end, name=f"kafka:{topic_name}")
