"""``pw.io.null`` — sink that discards output but still drives the graph.

reference: python/pathway/io/null/__init__.py (Rust NullWriter,
src/connectors/data_storage.rs:1395).
"""

from __future__ import annotations

from ...internals.table import Table
from .._subscribe import subscribe

__all__ = ["write"]


def write(table: Table) -> None:
    subscribe(table, on_change=lambda *a: None, name="null")
