"""``pw.io.plaintext`` — one row per line of text.

reference: python/pathway/io/plaintext/__init__.py.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

from ...internals.table import Table

__all__ = ["read"]


def read(
    path: str | Path,
    *,
    mode: str = "streaming",
    with_metadata: bool = False,
    **kwargs: Any,
) -> Table:
    from .. import fs

    return fs.read(
        path, format="plaintext", mode=mode, with_metadata=with_metadata, **kwargs
    )
