"""``pw.io.pyfilesystem`` — sources over PyFilesystem2 URLs
(reference: python/pathway/io/pyfilesystem).  Needs the ``fs`` package.
"""

from __future__ import annotations

import time as _time
from typing import Any

from ...internals.schema import schema_from_types
from ...internals.table import Table
from .._utils import input_table, with_metadata_schema
from ...internals.keys import ref_scalar
from ...internals.value import Json
from ..streaming import ConnectorSubject

__all__ = ["read"]


class _PyFsSubject(ConnectorSubject):
    _shared_source = True

    def __init__(self, source, path, mode, refresh_s, with_metadata, autocommit_ms):
        super().__init__(datasource_name=f"pyfs:{path}")
        self.source = source
        self.path = path
        self._mode = "static" if mode == "static" else "streaming"
        self.refresh_s = refresh_s
        self.with_metadata = with_metadata
        self._autocommit_ms = autocommit_ms
        self._seen: dict[str, tuple] = {}

    def _scan(self) -> None:
        current = {}
        for p in self.source.walk.files(self.path or "/"):
            info = self.source.getinfo(p, namespaces=["details"])
            current[p] = info.modified.isoformat() if info.modified else ""
        for p in list(self._seen):
            if p not in current:
                stamp, key, values = self._seen.pop(p)
                self._remove(key, values)
        for p, stamp in current.items():
            old = self._seen.get(p)
            if old is not None and old[0] == stamp:
                continue
            if old is not None:
                self._remove(old[1], old[2])
            data = self.source.readbytes(p)
            key = ref_scalar("__pyfs__", p)
            row = {"data": data}
            if self.with_metadata:
                row["_metadata"] = Json({"path": p, "modified_at": stamp})
            values = tuple(row.get(n) for n in self._column_names)
            self._add_inner(key, values)
            self._seen[p] = (stamp, key, values)
        self.commit()

    def run(self) -> None:
        self._scan()
        if self._mode == "static":
            return
        while not self._closed.is_set():
            _time.sleep(self.refresh_s)
            self._scan()


def read(
    source: Any,
    path: str = "",
    *,
    mode: str = "streaming",
    refresh_interval: float = 30.0,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    **kwargs: Any,
) -> Table:
    if isinstance(source, str):
        import fs  # optional dependency

        source = fs.open_fs(source)
    schema = schema_from_types(data=bytes)
    out_schema = with_metadata_schema(schema) if with_metadata else schema
    subject = _PyFsSubject(
        source, path, mode, refresh_interval, with_metadata, autocommit_duration_ms
    )
    subject._configure(out_schema, None)
    return input_table(out_schema, subject=subject)
