"""Production observability plane (ISSUE 15).

Four pillars, all riding the existing flight-recorder/metrics
discipline (families declared in ``internals/metrics_names.py``,
weak-registry providers on ``/status``, gated blocks on ``/v1/health``,
health probes never import jax):

* :mod:`~pathway_tpu.observability.hbm_ledger` — ONE process-wide
  registry of device-resident allocations.  Every HBM-holding subsystem
  (KNN indexes + their staged-scatter debt, sharded shards, tiered
  routers, paged-KV block pools, encoder/decoder param trees) registers
  a named entry; the ledger emits ``pathway_hbm_bytes{component=,shard=}``
  plus a process total, reconciled against the device runtime's
  ``memory_stats()`` when the backend exposes it.
* :mod:`~pathway_tpu.observability.slo` — per-endpoint latency
  histograms with OpenMetrics *exemplars* (a burning p99 bucket links
  straight to ``/v1/debug/traces?trace_id=``), SLO targets from
  ``PATHWAY_SLO_*`` knobs, multi-window burn rates (fast/slow, Google
  SRE workbook semantics) and ``ok|warn|burning`` verdicts on
  ``/v1/health`` — the payload a fleet router places load on.
* freshness SLO — connector read-time stamped through
  parse→split→embed→upsert→commit (``io/streaming.py`` +
  ``internals/monitoring.py``) so ``pathway_freshness_seconds``
  measures ingest→queryable lag end to end per connector, with the
  same burn-rate treatment.
* :mod:`~pathway_tpu.observability.profiler` — on-demand device
  profiling (``GET/POST /v1/debug/profile?ms=``): a bounded-spool
  ``jax.profiler`` trace window on TPU, a pure flight-recorder Perfetto
  export everywhere else; single-flight, capped duration.
* :mod:`~pathway_tpu.observability.federation` — fleet-wide telemetry:
  the router scrapes every replica's ``/status``, re-exposes each
  ``pathway_*`` family with a ``replica=`` label plus restart-safe
  fleet aggregates, computes fleet-level SLO burn verdicts from the
  federated latency histograms, and stitches one cross-process trace
  tree (router dispatch → replica request → device launch) on
  ``GET /v1/debug/trace?trace_id=``.

Import discipline: every module here is stdlib-only at import time
(plus the :mod:`internals.metrics_names` leaf) — jax is touched only
behind ``sys.modules`` gates, so health probes and metric scrapes never
initialize a device runtime.
"""

from __future__ import annotations

__all__ = ["hbm_ledger", "slo", "profiler", "federation"]
