"""Fleet-wide telemetry federation and cross-process trace stitching.

Two planes, both router-side (the replicas stay dumb — they already
expose ``/v1/debug/traces`` and ``/status``; this module only teaches the
router to *join* what N processes each know a fragment of):

* **Trace stitching** — one logical request crosses the router
  (dispatch + per-attempt spans), one or more replicas (request span,
  retrieval stages), and the generation plane (launch-guard spans under
  the same trace id).  :func:`stitch_trace` merges the fragments into a
  single parent-linked tree; a replica that cannot be reached marks the
  result ``incomplete`` instead of silently dropping its spans.

* **Metrics federation** — :class:`FederationState` parses each
  replica's OpenMetrics ``/status`` exposition, re-exposes every
  ``pathway_*`` family with a ``replica=`` label, and maintains
  restart-safe fleet aggregates for counters (a replica restart folds
  the last-seen value into a monotonic base instead of producing a
  negative rate).  The federated per-endpoint latency histograms feed
  fleet-level SLO burn verdicts through the SAME multi-window math the
  replicas use (:mod:`.slo` public helpers) — the router and a replica
  must agree about the same incident.

Kill switch: ``PATHWAY_FLEET_FEDERATION=0`` disables the scrape plane
entirely (the ``benchmarks/obs_overhead.py --fleet`` off-phase).
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable

__all__ = [
    "KNOWN_SPAN_KINDS",
    "KNOWN_SPAN_PREFIXES",
    "federation_enabled",
    "stitch_trace",
    "render_tree",
    "stitched_perfetto",
    "FederationState",
]


def federation_enabled() -> bool:
    """The ``PATHWAY_FLEET_FEDERATION`` kill switch (default on)."""
    return os.environ.get(
        "PATHWAY_FLEET_FEDERATION", "1"
    ).strip().lower() not in ("0", "false", "off", "no")


# ---------------------------------------------------------------------------
# trace schema: the renderer's known-kinds table
# ---------------------------------------------------------------------------

#: span name -> (plane, description).  The ``generate`` plane entries are
#: lint-pinned against the engine's ``_record_span`` call sites (tests
#: assert set equality in BOTH directions, the fault-site registry
#: idiom): a new launch guard must document itself here, and a stale
#: entry must not outlive its guard.
KNOWN_SPAN_KINDS: dict[str, tuple[str, str]] = {
    # generation launch guards (generation/engine.py)
    "kv:alloc": ("generate", "paged KV block allocation for one sequence"),
    "kv:prefix_match": (
        "generate", "copy-on-write prefix lookup in the paged pool"
    ),
    "kv:rebuild": (
        "generate", "KV-pool resurrection by replay re-prefill"
    ),
    "prefill": ("generate", "batched prompt prefill device launch"),
    "decode:step": ("generate", "one batched decode device launch"),
    "decode:verify": (
        "generate", "speculative draft verification device launch"
    ),
    # fleet routing (fleet/router.py)
    "fleet:dispatch": (
        "fleet", "router-side lifetime of one proxied request"
    ),
    "fleet:attempt": (
        "fleet", "one proxy attempt against one replica (siblings on failover)"
    ),
}

#: dynamic span-name prefixes (the suffix is a label, not a kind)
KNOWN_SPAN_PREFIXES: dict[str, tuple[str, str]] = {
    "tick:": ("scheduler", "deferred runtime batch execution"),
    "tier:migrate:": ("runtime", "background tier migration"),
}


def span_kind_info(name: str) -> tuple[str, str] | None:
    """Lookup a span name in the known-kinds schema (exact match first,
    then dynamic prefixes)."""
    info = KNOWN_SPAN_KINDS.get(name)
    if info is not None:
        return info
    for prefix, pinfo in KNOWN_SPAN_PREFIXES.items():
        if name.startswith(prefix):
            return pinfo
    return None


# ---------------------------------------------------------------------------
# trace stitching
# ---------------------------------------------------------------------------

def stitch_trace(
    trace_id: str,
    router_spans: list[dict[str, Any]],
    replica_payloads: dict[str, dict[str, Any] | None],
) -> dict[str, Any]:
    """Merge the router's own spans with per-replica fragments into one
    parent-linked tree.

    ``replica_payloads`` maps replica name to its ``/v1/debug/traces``
    JSON body (``{"spans": [...]}``) or ``None`` when the fetch failed.
    An unreachable replica marks the stitched result ``incomplete``
    (partial evidence beats a 500); a span whose ``parent_id`` is not in
    the merged set becomes a root marked ``orphan`` (its parent span was
    dropped from some ring, or lives on an unreachable replica)."""
    spans: list[dict[str, Any]] = []
    seen: set[str] = set()
    incomplete = False
    replicas: dict[str, str] = {}

    def _add(raw: dict[str, Any], source: str) -> None:
        sid = raw.get("span_id")
        if sid is not None:
            if sid in seen:
                return  # router + replica can both hold the same span
            seen.add(sid)
        d = dict(raw)
        d["replica"] = source
        info = span_kind_info(str(d.get("name", "")))
        if info is not None:
            d["kind_info"] = {"plane": info[0], "description": info[1]}
        spans.append(d)

    for raw in router_spans:
        _add(raw, "router")
    for name in sorted(replica_payloads):
        payload = replica_payloads[name]
        if not isinstance(payload, dict) or "spans" not in payload:
            replicas[name] = "unreachable"
            incomplete = True
            continue
        replicas[name] = "ok"
        for raw in payload.get("spans") or []:
            if not isinstance(raw, dict):
                continue
            if raw.get("trace_id") not in (None, trace_id):
                continue  # defensive: a replica must only send this trace
            _add(raw, name)

    spans.sort(key=lambda d: (float(d.get("start_s", 0.0) or 0.0),
                              str(d.get("name", ""))))
    by_id = {d["span_id"]: d for d in spans if d.get("span_id")}
    children: dict[str, list[dict[str, Any]]] = {}
    roots: list[dict[str, Any]] = []
    for d in spans:
        pid = d.get("parent_id")
        if pid and pid in by_id and by_id[pid] is not d:
            children.setdefault(pid, []).append(d)
        else:
            if pid:
                d["orphan"] = True
            roots.append(d)

    # nest iteratively with a visited set: corrupt parent links (a
    # cycle) degrade to extra roots instead of infinite recursion
    visited: set[int] = set()

    def _node(d: dict[str, Any]) -> dict[str, Any]:
        visited.add(id(d))
        out = dict(d)
        kids = children.get(d.get("span_id") or "", [])
        out["children"] = [
            _node(k) for k in kids if id(k) not in visited
        ]
        return out

    tree = [_node(d) for d in roots if id(d) not in visited]
    return {
        "trace_id": trace_id,
        "incomplete": incomplete,
        "replicas": replicas,
        "span_count": len(spans),
        "spans": spans,
        "tree": tree,
    }


def render_tree(stitched: dict[str, Any]) -> str:
    """ASCII rendering of a stitched tree — one line per span, indented
    by depth, annotated from the known-kinds schema."""
    lines = [
        f"trace {stitched['trace_id']}"
        + (" (incomplete)" if stitched.get("incomplete") else "")
    ]

    def _walk(node: dict[str, Any], depth: int) -> None:
        info = node.get("kind_info") or {}
        desc = f" — {info['description']}" if info.get("description") else ""
        orphan = " [orphan]" if node.get("orphan") else ""
        lines.append(
            "  " * depth
            + f"{node.get('name', '?')} "
            f"({float(node.get('duration_ms', 0.0) or 0.0):.3f} ms) "
            f"@{node.get('replica', '?')}{orphan}{desc}"
        )
        for kid in node.get("children", []):
            _walk(kid, depth + 1)

    for root in stitched.get("tree", []):
        _walk(root, 1)
    return "\n".join(lines)


def stitched_perfetto(stitched: dict[str, Any]) -> dict[str, Any]:
    """Chrome-tracing export of a stitched tree, reusing the profiler's
    span-export path (one converter, not two)."""
    from ..internals.flight_recorder import FlightRecorder, Span

    spans = [
        Span(
            str(d.get("name", "?")),
            str(d.get("category", "?")),
            float(d.get("start_s", 0.0) or 0.0),
            float(d.get("duration_ms", 0.0) or 0.0),
            d.get("trace_id"),
            d.get("span_id"),
            d.get("parent_id"),
            {**(d.get("attrs") or {}), "replica": d.get("replica", "")},
        )
        for d in stitched.get("spans", [])
    ]
    return FlightRecorder.perfetto(spans)


# ---------------------------------------------------------------------------
# OpenMetrics exposition parsing (the scrape side)
# ---------------------------------------------------------------------------

_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) ([a-z]+)\s*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # sample name
    r"(?:\{(.*)\})?"                # label set (raw, unsplit)
    r"\s+(\S+)\s*$"                 # value
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

#: sample-name suffixes that resolve to a complex family's base name
_FAMILY_SUFFIXES = ("_bucket", "_sum", "_count", "_created")


def _unescape(value: str) -> str:
    out: list[str] = []
    it = iter(range(len(value)))
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_labels(labels_str: str | None) -> dict[str, str]:
    if not labels_str:
        return {}
    return {
        m.group(1): _unescape(m.group(2))
        for m in _LABEL_RE.finditer(labels_str)
    }


def parse_exposition(text: str) -> dict[str, dict[str, Any]]:
    """Parse one OpenMetrics exposition into
    ``{family: {"type": str, "samples": [(sample_name, labels_str, value)]}}``.

    Only ``pathway_*`` families are kept.  Exemplar suffixes
    (``... # {trace_id="..."} v ts``) are stripped before the sample
    regex runs — the ``# TYPE``-driven family table resolves
    ``_bucket``/``_sum``/``_count`` sample names onto their histogram
    family."""
    families: dict[str, dict[str, Any]] = {}
    types: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                types[m.group(1)] = m.group(2)
            continue
        # exemplars ride after ` # ` on bucket lines; the label regex
        # must never see the exemplar's own brace group
        body = line.split(" # ", 1)[0].rstrip()
        m = _SAMPLE_RE.match(body)
        if m is None:
            continue
        sname, labels_str, raw_value = m.group(1), m.group(2), m.group(3)
        try:
            value = float(raw_value)
        except ValueError:
            continue
        family = sname if sname in types else None
        if family is None:
            for suffix in _FAMILY_SUFFIXES:
                if sname.endswith(suffix) and sname[: -len(suffix)] in types:
                    family = sname[: -len(suffix)]
                    break
        if family is None:
            family = sname
        if not family.startswith("pathway_"):
            continue
        fam = families.get(family)
        if fam is None:
            fam = families[family] = {
                "type": types.get(family, "gauge"),
                "samples": [],
            }
        fam["samples"].append((sname, labels_str or "", value))
    return families


def _inject_replica_label(
    sname: str, labels_str: str, replica: str
) -> str:
    from ..internals.metrics_names import escape_label_value

    lab = f'replica="{escape_label_value(replica)}"'
    if labels_str:
        lab = f"{lab},{labels_str}"
    return f"{sname}{{{lab}}}"


def _fmt(value: float) -> str:
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


# ---------------------------------------------------------------------------
# federation state (scrapes, aggregates, fleet SLO)
# ---------------------------------------------------------------------------

#: families the federation plane itself owns — never re-exposed from a
#: replica (a collision would emit two TYPE lines for one family)
_OWN_FAMILIES = frozenset({
    "pathway_fleet_aggregate_total",
    "pathway_fleet_scrapes_total",
    "pathway_fleet_scrape_errors_total",
    "pathway_fleet_slo_burn_rate",
    "pathway_fleet_slo_verdict",
})

#: the per-endpoint latency histogram the fleet SLO verdicts read
_LATENCY_FAMILY = "pathway_endpoint_latency_ms"

#: a sample that already carries a ``replica=`` label was federated by
#: some OTHER router (a replica whose process embeds one, or a tiered
#: router topology): folding it again would double-count aggregates and
#: nest ``replica=`` labels one level deeper per scrape cycle
_FEDERATED_RE = re.compile(r'(?:^|,)replica="')


def _already_federated(labels_str: str) -> bool:
    return bool(_FEDERATED_RE.search(labels_str))


class FederationState:
    """Router-side scrape state: per-replica re-exposition, restart-safe
    counter aggregates, and fleet SLO burn rings.

    Thread-safe; the router calls :meth:`note_scrape` from its poller
    thread and :meth:`openmetrics_lines` / :meth:`status` from the
    aiohttp loop."""

    def __init__(
        self,
        *,
        clock: Callable[[], float] = time.monotonic,
        stale_after_s: float | None = None,
    ):
        self._lock = threading.Lock()
        self._clock = clock
        self.stale_after_s = (
            stale_after_s
            if stale_after_s is not None
            else float(os.environ.get("PATHWAY_FLEET_SCRAPE_STALE_S", "15.0"))
        )
        #: latest parse per replica (re-exposition source)
        self._families: dict[str, dict[str, dict[str, Any]]] = {}
        self._scraped_at: dict[str, float] = {}
        #: counter folding: aggregate(key) = retired + Σ(base + last)
        #: over replicas — monotonic across restarts AND drops
        self._last: dict[str, dict[tuple[str, str], float]] = {}
        self._base: dict[str, dict[tuple[str, str], float]] = {}
        self._retired: dict[tuple[str, str], float] = {}
        #: fleet SLO: per-replica (count, bad) baselines and the shared
        #: per-endpoint per-second rings the burn windows read
        self._slo_last: dict[str, dict[str, tuple[float, float]]] = {}
        self._slo_series: dict[str, deque] = {}
        self.scrapes_total = 0
        self.scrape_errors_total = 0

    # -- scrape ingestion -------------------------------------------------
    def note_scrape(self, replica: str, text: str) -> None:
        """Fold one replica ``/status`` body in."""
        families = parse_exposition(text)
        now = self._clock()
        with self._lock:
            self.scrapes_total += 1
            self._families[replica] = families
            self._scraped_at[replica] = now
            last = self._last.setdefault(replica, {})
            base = self._base.setdefault(replica, {})
            for family, fam in families.items():
                if fam["type"] != "counter" or family in _OWN_FAMILIES:
                    continue
                for sname, labels_str, value in fam["samples"]:
                    if sname != family or _already_federated(labels_str):
                        continue  # _created etc. are not the counter
                    key = (family, labels_str)
                    prev = last.get(key)
                    if prev is not None and value < prev:
                        # counter went backwards without an epoch signal:
                        # an in-place restart — fold, stay monotonic
                        base[key] = base.get(key, 0.0) + prev
                    last[key] = value
            self._ingest_slo_locked(replica, families, now)

    def note_scrape_error(self, replica: str) -> None:
        with self._lock:
            self.scrape_errors_total += 1

    def _ingest_slo_locked(
        self,
        replica: str,
        families: dict[str, dict[str, Any]],
        now: float,
    ) -> None:
        from . import slo

        fam = families.get(_LATENCY_FAMILY)
        if fam is None:
            return
        # per endpoint: cumulative request count and the cumulative
        # count inside the latency target (largest bucket <= target)
        counts: dict[str, float] = {}
        good: dict[str, tuple[float, float]] = {}  # endpoint -> (le, cum)
        for sname, labels_str, value in fam["samples"]:
            if _already_federated(labels_str):
                continue
            labels = parse_labels(labels_str)
            endpoint = labels.get("endpoint")
            if not endpoint:
                continue
            if sname == f"{_LATENCY_FAMILY}_count":
                counts[endpoint] = value
            elif sname == f"{_LATENCY_FAMILY}_bucket":
                target = slo.latency_target_ms(endpoint)
                if target <= 0.0:
                    continue
                try:
                    le = float(labels.get("le", "nan"))
                except ValueError:
                    continue
                best = good.get(endpoint)
                if le <= target and (best is None or le > best[0]):
                    good[endpoint] = (le, value)
        baselines = self._slo_last.setdefault(replica, {})
        for endpoint, count in counts.items():
            if endpoint not in good:
                continue  # no configured target -> no fleet objective
            bad = max(0.0, count - good[endpoint][1])
            prev = baselines.get(endpoint)
            baselines[endpoint] = (count, bad)
            if prev is None:
                continue  # first scrape after (re)start: baseline only
            dn, dbad = count - prev[0], bad - prev[1]
            if dn <= 0 or dbad < 0:
                continue  # restart raced the epoch signal: re-baseline
            ring = self._slo_series.get(endpoint)
            if ring is None:
                ring = self._slo_series[endpoint] = deque()
            sec = int(now)
            if ring and ring[-1][0] == sec:
                ring[-1][1] += dn
                ring[-1][2] += dbad
            else:
                ring.append([sec, dn, dbad])
            # prune beyond the slow window (the longest reader)
            horizon = slo.burn_settings()["slow_s"]
            while ring and now - ring[0][0] > horizon:
                ring.popleft()

    # -- membership hooks -------------------------------------------------
    def reset_replica(self, replica: str) -> None:
        """Epoch restart: the NEXT scrape's counters start near zero.
        Fold every last-seen value into the monotonic base now so the
        aggregate never decreases, and drop the SLO delta baselines so
        the first post-restart scrape only re-baselines."""
        with self._lock:
            last = self._last.get(replica, {})
            base = self._base.setdefault(replica, {})
            for key, value in last.items():
                base[key] = base.get(key, 0.0) + value
                last[key] = 0.0
            self._slo_last.pop(replica, None)

    def drop_replica(self, replica: str) -> None:
        """Replica left the fleet: retire its contribution (aggregates
        stay monotonic) and DROP its re-exposed series (stale series
        vanish instead of freezing at their last value)."""
        with self._lock:
            last = self._last.pop(replica, {})
            base = self._base.pop(replica, {})
            for key in set(last) | set(base):
                self._retired[key] = (
                    self._retired.get(key, 0.0)
                    + base.get(key, 0.0)
                    + last.get(key, 0.0)
                )
            self._families.pop(replica, None)
            self._scraped_at.pop(replica, None)
            self._slo_last.pop(replica, None)

    # -- read side --------------------------------------------------------
    def _live_replicas_locked(self, now: float) -> list[str]:
        return sorted(
            n
            for n, at in self._scraped_at.items()
            if now - at <= self.stale_after_s
        )

    def verdicts(self) -> dict[str, Any]:
        """Fleet-level burn verdicts from the federated latency
        histograms — same windows, budget, and thresholds as a replica's
        own verdict."""
        from . import slo

        cfg = slo.burn_settings()
        now = self._clock()
        endpoints: dict[str, Any] = {}
        worst = "ok"
        with self._lock:
            series = {ep: list(ring) for ep, ring in self._slo_series.items()}
        for endpoint in sorted(series):
            fast, n_fast = _ring_burn(
                series[endpoint], cfg["fast_s"], slo.LATENCY_BUDGET, now
            )
            slow, n_slow = _ring_burn(
                series[endpoint], cfg["slow_s"], slo.LATENCY_BUDGET, now
            )
            verdict = slo.burn_verdict(fast, slow, cfg)
            endpoints[endpoint] = {
                "verdict": verdict,
                "burn_fast": round(fast, 3),
                "burn_slow": round(slow, 3),
                "samples_fast": n_fast,
                "samples_slow": n_slow,
                "p99_ms": slo.latency_target_ms(endpoint),
            }
            worst = slo.worse_verdict(worst, verdict)
        return {"verdict": worst, "endpoints": endpoints}

    def status(self) -> dict[str, Any]:
        now = self._clock()
        with self._lock:
            replicas = {
                n: {
                    "age_s": round(now - at, 3),
                    "stale": (now - at) > self.stale_after_s,
                }
                for n, at in sorted(self._scraped_at.items())
            }
            scrapes = self.scrapes_total
            errors = self.scrape_errors_total
        out = self.verdicts()
        out["replicas"] = replicas
        out["scrapes"] = scrapes
        out["scrape_errors"] = errors
        return out

    def openmetrics_lines(
        self, skip_families: frozenset | set | None = None
    ) -> list[str]:
        """Federated exposition: per-replica re-exposed families (live
        replicas only — stale series are dropped, not frozen), monotonic
        counter aggregates, scrape counters, and the fleet SLO gauges."""
        from ..internals.metrics_names import escape_label_value

        skip = set(skip_families or ()) | set(_OWN_FAMILIES)
        now = self._clock()
        lines: list[str] = []
        with self._lock:
            live = self._live_replicas_locked(now)
            # family -> (type, [(replica, sname, labels_str, value)])
            merged: dict[str, tuple[str, list]] = {}
            for replica in live:
                for family, fam in self._families[replica].items():
                    if family in skip:
                        continue
                    entry = merged.get(family)
                    if entry is None:
                        entry = merged[family] = (fam["type"], [])
                    for sname, labels_str, value in fam["samples"]:
                        if _already_federated(labels_str):
                            continue
                        entry[1].append((replica, sname, labels_str, value))
            aggregates: dict[tuple[str, str], float] = dict(self._retired)
            for replica in self._last:
                base = self._base.get(replica, {})
                last = self._last[replica]
                for key in set(last) | set(base):
                    aggregates[key] = (
                        aggregates.get(key, 0.0)
                        + base.get(key, 0.0)
                        + last.get(key, 0.0)
                    )
            scrapes = self.scrapes_total
            errors = self.scrape_errors_total
        for family in sorted(merged):
            ftype, samples = merged[family]
            if not samples:
                continue  # everything filtered as already-federated
            lines.append(f"# TYPE {family} {ftype}")
            for replica, sname, labels_str, value in samples:
                lines.append(
                    f"{_inject_replica_label(sname, labels_str, replica)}"
                    f" {_fmt(value)}"
                )
        lines.append("# TYPE pathway_fleet_aggregate_total counter")
        for (family, labels_str), value in sorted(aggregates.items()):
            lab = f'family="{escape_label_value(family)}"'
            if labels_str:
                lab = f"{lab},{labels_str}"
            lines.append(
                f"pathway_fleet_aggregate_total{{{lab}}} {_fmt(value)}"
            )
        lines.append("# TYPE pathway_fleet_scrapes_total counter")
        lines.append(f"pathway_fleet_scrapes_total {scrapes}")
        lines.append("# TYPE pathway_fleet_scrape_errors_total counter")
        lines.append(f"pathway_fleet_scrape_errors_total {errors}")
        fleet = self.verdicts()
        if fleet["endpoints"]:
            lines.append("# TYPE pathway_fleet_slo_burn_rate gauge")
            for endpoint, obj in fleet["endpoints"].items():
                safe = escape_label_value(endpoint)
                for window in ("fast", "slow"):
                    lines.append(
                        "pathway_fleet_slo_burn_rate"
                        f'{{endpoint="{safe}",window="{window}"}} '
                        f'{obj[f"burn_{window}"]}'
                    )
            lines.append("# TYPE pathway_fleet_slo_verdict gauge")
            rank = {"ok": 0, "warn": 1, "burning": 2}
            for endpoint, obj in fleet["endpoints"].items():
                safe = escape_label_value(endpoint)
                lines.append(
                    "pathway_fleet_slo_verdict"
                    f'{{endpoint="{safe}"}} '
                    f'{rank.get(obj["verdict"], 0)}'
                )
        return lines


def _ring_burn(
    cells: list, window_s: float, budget: float, now: float
) -> tuple[float, int]:
    """Burn rate over the trailing window — the :class:`slo._Series`
    rule applied to the fleet ring's ``[sec, n, bad]`` cells."""
    n = 0
    bad = 0.0
    for sec, cnt, b in reversed(cells):
        if now - sec > window_s:
            break  # append-ordered: everything older too
        n += int(cnt)
        bad += b
    if n == 0:
        return 0.0, 0
    return (bad / n) / max(budget, 1e-9), n
