"""Unified HBM ledger: one registry for every device-resident byte.

Before this module each subsystem reported its HBM footprint
independently (``pathway_index_hbm_bytes``, the tiering/generation
status blocks, ...) with no total and no reconciliation — an operator
sizing corpus-per-chip had to add four gauges by hand and still could
not see staged-scatter debt or parameter trees.  Now every
device-resident subsystem registers a named allocation here:

* ``DeviceKnnIndex`` matrix/codes/scales/rescore-ring (+ a separate
  ``knn_staged:*`` entry for device-staged scatter debt),
* ``ShardedKnnIndex`` per-shard (the ``shard`` label),
* the tiered index's router centroid matrix (its hot tier is itself a
  ``DeviceKnnIndex`` and registers through that path — no double count),
* paged-KV block pools (``kv_pool:*``),
* encoder/decoder parameter trees (``encoder_params:*`` /
  ``decoder_params:*``).

The ledger emits ``pathway_hbm_bytes{component=,shard=}`` plus
``pathway_hbm_total_bytes`` and, when the device runtime exposes
``memory_stats()`` (TPU), reconciles the attributed total against the
device's ``bytes_in_use``: drift beyond ``PATHWAY_HBM_DRIFT_FRAC``
(default 0.15) flags an ``unattributed`` component LOUDLY (log + metric
+ health block).  Off-TPU the ledger is exact by construction — every
entry reads the owning subsystem's own ``hbm_bytes()`` — and the
reconcile is skipped.

Entries hold a WEAK reference to their owner plus a pure function
``bytes_fn(owner) -> int | dict[shard_label, int]``: a collected index
drops out of the ledger with its owner, and registering can never
extend an owner's lifetime.  Import discipline: stdlib only; jax is
touched exclusively behind a ``sys.modules`` gate inside
:func:`device_memory_view` (health probes never initialize a backend).
"""

from __future__ import annotations

import itertools
import logging
import sys
import threading
import weakref
from typing import Any, Callable

from ..internals.config import env_float as _env_float

__all__ = [
    "HbmLedger",
    "get_ledger",
    "reset_ledger",
    "hbm_status",
    "capacity_status",
    "device_memory_view",
]

logger = logging.getLogger("pathway_tpu")


def drift_frac() -> float:
    """``PATHWAY_HBM_DRIFT_FRAC``: reconcile tolerance as a fraction of
    the device's ``bytes_in_use`` (default 0.15 — XLA scratch, compiled
    executables and allocator slack legitimately sit outside any
    subsystem's ledger entry)."""
    return max(0.0, _env_float("PATHWAY_HBM_DRIFT_FRAC", 0.15))


class HbmLedger:
    """Process-wide registry of named device allocations.

    ``register`` returns a token for explicit :meth:`release`; entries
    also vanish automatically when their (weakly-held) owner is
    collected.  ``bytes_fn`` is called at snapshot time so entries track
    live growth (capacity doublings, pool swaps) with zero bookkeeping
    at the allocation site."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: token -> (component, weakref(owner), bytes_fn)
        self._entries: dict[int, tuple[str, weakref.ref, Callable]] = {}
        self._seq = itertools.count()
        #: sticky reconcile flag: flips are logged once per transition,
        #: not once per scrape
        self._drift_flagged = False
        #: size trigger for the in-register dead-entry sweep (doubles
        #: after each sweep so churn-heavy registration stays O(1)
        #: amortized)
        self._sweep_at = 64

    def register(
        self, component: str, owner: Any, bytes_fn: Callable[[Any], Any]
    ) -> int:
        """Add one named allocation.  ``bytes_fn(owner)`` must return an
        ``int`` (single allocation) or a ``dict[shard_label, int]``
        (per-shard breakdown; the labels become the ``shard`` label on
        the emitted series).

        Deliberately NO weakref callback: a finalizer firing from
        cyclic GC mid-``register``/``entries`` would re-enter this
        non-reentrant lock on the same thread and deadlock the scrape.
        Dead entries are skipped at snapshot time and swept there."""
        return self._register(owner, bytes_fn, lambda _t: str(component))

    def register_unique(
        self, prefix: str, owner: Any, bytes_fn: Callable[[Any], Any]
    ) -> int:
        """:meth:`register` with a process-unique ``#<seq>`` label
        suffix — for registrants whose natural name can repeat (two
        default-named decode sessions, two encoders of one checkpoint):
        duplicate identical-label series would make the whole
        OpenMetrics exposition invalid, and every module re-growing its
        own counter for this was the same idiom copied three times."""
        return self._register(owner, bytes_fn, lambda t: f"{prefix}#{t}")

    def _register(
        self, owner: Any, bytes_fn: Callable[[Any], Any], label_fn: Callable
    ) -> int:
        token = next(self._seq)
        ref = weakref.ref(owner)
        with self._lock:
            self._entries[token] = (label_fn(token), ref, bytes_fn)
            # size-triggered sweep: snapshot surfaces also sweep, but a
            # headless process that churns owners WITHOUT ever being
            # scraped must not accumulate dead tuples unboundedly
            if len(self._entries) >= self._sweep_at:
                for t in [
                    t
                    for t, (_c, r, _f) in self._entries.items()
                    if r() is None
                ]:
                    del self._entries[t]
                self._sweep_at = max(64, 2 * len(self._entries))
        _ensure_provider()
        return token

    def release(self, token: int) -> None:
        with self._lock:
            self._entries.pop(token, None)

    # -- snapshots -------------------------------------------------------
    def entries(self) -> list[tuple[str, str | None, int]]:
        """``(component, shard, bytes)`` rows over every live entry,
        sorted for stable exposition.  A ``bytes_fn`` that raises drops
        that entry from the snapshot (never from the ledger — a
        transient failure must not unregister the owner) rather than
        failing the scrape."""
        with self._lock:
            snap = list(self._entries.items())
        rows: list[tuple[str, str | None, int]] = []
        dead: list[int] = []
        for token, (component, ref, fn) in snap:
            owner = ref()
            if owner is None:
                dead.append(token)
                continue
            try:
                val = fn(owner)
            except Exception:  # noqa: BLE001 — a dying owner must not kill /status
                continue
            if isinstance(val, dict):
                for shard, b in val.items():
                    rows.append((component, str(shard), int(b)))
            else:
                rows.append((component, None, int(val)))
        if dead:
            # sweep collected owners here, NOT via weakref finalizers —
            # see register() for why
            with self._lock:
                for token in dead:
                    self._entries.pop(token, None)
        rows.sort(key=lambda r: (r[0], r[1] or ""))
        return rows

    def total_bytes(self) -> int:
        return sum(b for _, _, b in self.entries())

    def reconcile(self, attributed: int | None = None) -> dict[str, Any] | None:
        """Compare the attributed total against the device runtime's own
        accounting.  ``None`` when the backend exposes no memory stats
        (CPU/interpret — the ledger is exact by construction there).
        Drift beyond ``PATHWAY_HBM_DRIFT_FRAC`` flags ``unattributed``
        loudly; re-converging logs the all-clear once.  Callers that
        already walked the entries pass ``attributed`` so a scrape runs
        every ``bytes_fn`` (param-tree walks included) once, not twice."""
        view = device_memory_view()
        if view is None:
            return None
        if attributed is None:
            attributed = self.total_bytes()
        in_use = int(view["bytes_in_use"])
        unattributed = max(0, in_use - attributed)
        frac = unattributed / max(in_use, 1)
        flagged = frac > drift_frac()
        with self._lock:
            # check-then-set under the lock: concurrent /status and
            # /v1/health probes crossing the threshold together must log
            # the transition once, as the docstring promises
            transition = flagged != self._drift_flagged
            self._drift_flagged = flagged
        if transition:
            if flagged:
                logger.warning(
                    "HBM ledger drift: device reports %d bytes in use but "
                    "only %d are attributed (unattributed %d = %.1f%% > "
                    "PATHWAY_HBM_DRIFT_FRAC=%.2f) — a device-resident "
                    "allocation is missing its ledger registration",
                    in_use, attributed, unattributed, 100 * frac, drift_frac(),
                )
            else:
                logger.info(
                    "HBM ledger drift cleared (unattributed %.1f%%)", 100 * frac
                )
        return {
            "bytes_in_use": in_use,
            "bytes_limit": int(view["bytes_limit"]) if view.get("bytes_limit") else None,
            "attributed_bytes": attributed,
            "unattributed_bytes": unattributed,
            "unattributed_frac": round(frac, 4),
            "drift_frac_limit": drift_frac(),
            "flagged": flagged,
        }


def tree_nbytes(tree: Any) -> int:
    """Sum of ``.nbytes`` over a pytree's array leaves — the ledger
    ``bytes_fn`` body for model parameter trees.  Gated on jax already
    being imported (a tree only exists if it is), 0 otherwise."""
    if "jax" not in sys.modules:
        return 0
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:  # noqa: BLE001 — a torn-down runtime must not kill /status
        return 0
    return int(sum(int(getattr(x, "nbytes", 0)) for x in leaves))


def device_memory_view() -> dict[str, int] | None:
    """Aggregate ``memory_stats()`` over the local devices, or ``None``
    when unavailable.  Gated on jax ALREADY being imported — a metrics
    scrape or health probe must never initialize the device runtime."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend not initialized / gone
        return None
    in_use = 0
    limit = 0
    seen = False
    for dev in devices:
        stats_fn = getattr(dev, "memory_stats", None)
        if stats_fn is None:
            continue
        try:
            stats = stats_fn()
        except Exception:  # noqa: BLE001 — CPU backends raise/return None
            continue
        if not stats or "bytes_in_use" not in stats:
            continue
        seen = True
        in_use += int(stats.get("bytes_in_use", 0))
        limit += int(stats.get("bytes_limit", 0))
    if not seen:
        return None
    return {"bytes_in_use": in_use, "bytes_limit": limit}


_ledger_lock = threading.Lock()
_ledger: HbmLedger | None = None


def get_ledger() -> HbmLedger:
    global _ledger
    led = _ledger
    if led is None:
        with _ledger_lock:
            if _ledger is None:
                _ledger = HbmLedger()
            led = _ledger
    return led


def reset_ledger() -> None:
    """Test isolation hook: drop every registration."""
    global _ledger
    with _ledger_lock:
        _ledger = None


# ---------------------------------------------------------------------------
# /status provider + /v1/health capacity block
# ---------------------------------------------------------------------------


class _LedgerMetricsProvider:
    """``pathway_hbm_bytes{component=,shard=}`` + ``pathway_hbm_total_bytes``
    (+ ``pathway_hbm_unattributed_bytes`` while the reconcile is flagged)."""

    def stats(self) -> dict:
        return hbm_status() or {}

    def openmetrics_lines(self) -> list[str]:
        from ..internals.metrics_names import escape_label_value

        led = get_ledger()
        rows = led.entries()
        lines = ["# TYPE pathway_hbm_bytes gauge"]
        total = 0
        for component, shard, b in rows:
            total += b
            labels = f'component="{escape_label_value(component)}"'
            if shard is not None:
                labels += f',shard="{escape_label_value(shard)}"'
            lines.append(f"pathway_hbm_bytes{{{labels}}} {b}")
        recon = led.reconcile(attributed=total)
        if recon is not None and recon["flagged"]:
            lines.append(
                'pathway_hbm_bytes{component="unattributed"} '
                f'{recon["unattributed_bytes"]}'
            )
            lines.append("# TYPE pathway_hbm_unattributed_bytes gauge")
            lines.append(
                f'pathway_hbm_unattributed_bytes {recon["unattributed_bytes"]}'
            )
        lines.append("# TYPE pathway_hbm_total_bytes gauge")
        lines.append(f"pathway_hbm_total_bytes {total}")
        return lines


def _ensure_provider() -> None:
    from ..internals.monitoring import register_metrics_provider_once

    register_metrics_provider_once("hbm_ledger", _LedgerMetricsProvider)


def hbm_status() -> dict[str, Any] | None:
    """Ledger snapshot for surfaces: per-component bytes (shard entries
    keyed ``component/shard``), the attributed total, and the reconcile
    result when a device runtime exposes one."""
    led = get_ledger()
    rows = led.entries()
    if not rows:
        return None
    components: dict[str, int] = {}
    for component, shard, b in rows:
        key = component if shard is None else f"{component}/{shard}"
        components[key] = components.get(key, 0) + b
    total = sum(components.values())
    out: dict[str, Any] = {
        "total_bytes": total,
        "components": components,
    }
    recon = led.reconcile(attributed=total)
    if recon is not None:
        out["device"] = recon
    return out


def capacity_status() -> dict[str, Any] | None:
    """The ``"capacity"`` block on ``/v1/health`` — the per-replica
    payload a least-loaded fleet router (ROADMAP item 4) places load on:
    attributed HBM total + free HBM (when the runtime reports it) +
    device-tick runtime occupancy (queue depths per QoS class)."""
    out: dict[str, Any] = {}
    hbm = hbm_status()
    if hbm is not None:
        cap: dict[str, Any] = {
            "hbm_total_bytes": hbm["total_bytes"],
            "hbm_components": hbm["components"],
        }
        device = hbm.get("device")
        if device is not None:
            if device.get("bytes_limit"):
                cap["hbm_free_bytes"] = max(
                    0, device["bytes_limit"] - device["bytes_in_use"]
                )
            cap["hbm_device"] = device
        out.update(cap)
    # runtime occupancy: read-only, never spawns the executor thread
    try:
        mod = sys.modules.get("pathway_tpu.runtime.executor")
        if mod is not None:
            occ = mod.runtime_capacity_if_active()
            if occ is not None:
                out["runtime"] = occ
    except Exception:  # noqa: BLE001 — capacity must never raise
        pass
    return out or None
