"""SLO engine: per-endpoint burn rates with exemplar-linked histograms.

The flight recorder answers "where did THIS request's time go"; nothing
answered "is the service healthy".  This module grows per-endpoint
latency histograms from the request spans the tracing middleware already
finishes, attaches OpenMetrics *exemplars* carrying the trace id (a
burning p99 bucket links straight to ``/v1/debug/traces?trace_id=``),
and evaluates SLO targets as multi-window burn rates — Google SRE
workbook semantics, no collector required:

* targets: ``PATHWAY_SLO_<ENDPOINT>_P99_MS`` (latency: at most 1% of
  requests may exceed the target) and ``PATHWAY_SLO_<ENDPOINT>_AVAIL``
  (availability: at most ``1 - target`` of requests may 5xx), where
  ``<ENDPOINT>`` is the route with the ``/v1/`` prefix stripped,
  non-alphanumerics mapped to ``_`` and uppercased
  (``/v1/retrieve`` → ``RETRIEVE``, ``/v1/pw_ai_answer`` →
  ``PW_AI_ANSWER``);
* burn rate = (bad fraction in window) / (error budget): a steady burn
  of 1.0 spends exactly the budget over the SLO period;
* two windows — fast ``PATHWAY_SLO_FAST_S`` (default 300 s) and slow
  ``PATHWAY_SLO_SLOW_S`` (default 3600 s) — over a bounded in-process
  ring of PER-SECOND aggregate buckets (``PATHWAY_SLO_RING`` buckets,
  default 8192 ≈ 2.3 h of retention at ANY request rate).  Verdict per endpoint: ``burning`` when BOTH windows
  burn at ≥ ``PATHWAY_SLO_BURN_HOT`` (14.4), ``warn`` when both ≥
  ``PATHWAY_SLO_BURN_WARN`` (6.0) or either ≥ the hot threshold, else
  ``ok``.  The multi-window AND is what makes the verdict flip to
  burning within the fast window under an incident and recover within
  the slow window after it — a one-window rule either pages late or
  flaps.

Freshness rides the same machinery: the streaming driver's end-to-end
connector lag observations (``pathway_freshness_seconds{connector=}``)
feed per-connector series with ``PATHWAY_SLO_FRESHNESS_S`` as the
target.  ``slo_status()`` is the ``"slo"`` block on ``/v1/health`` —
next to the ``"capacity"`` block, the exact payload a fleet router
consumes.

Import discipline: stdlib + the :mod:`internals.metrics_names` leaf
only; this module never imports jax.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from ..internals.config import env_float as _env_float
from ..internals.config import env_int as _env_int
from ..internals.metrics_names import Histogram, escape_label_value

__all__ = [
    "observe_request",
    "observe_freshness",
    "slo_status",
    "slo_metrics_lines",
    "endpoint_env_key",
    "reset_slo",
    "burn_settings",
    "burn_verdict",
    "worse_verdict",
    "latency_target_ms",
    "LATENCY_BUDGET",
]

#: latency histogram bucket upper bounds (ms) — wider than the stage
#: buckets: endpoint totals include model calls and decode streams
_LATENCY_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)

#: cardinality bound: an unknown-path scan must not mint unbounded
#: series — beyond the cap, observations aggregate under "other"
_MAX_ENDPOINTS = 64

#: fixed latency-objective budget: a p99 target means 1% of requests may
#: exceed it
_LATENCY_BUDGET = 0.01


def _settings() -> dict[str, float]:
    return {
        "fast_s": max(0.001, _env_float("PATHWAY_SLO_FAST_S", 300.0)),
        "slow_s": max(0.001, _env_float("PATHWAY_SLO_SLOW_S", 3600.0)),
        "burn_hot": _env_float("PATHWAY_SLO_BURN_HOT", 14.4),
        "burn_warn": _env_float("PATHWAY_SLO_BURN_WARN", 6.0),
        "ring": max(16, _env_int("PATHWAY_SLO_RING", 8192)),
    }


def endpoint_env_key(path: str) -> str:
    """``/v1/pw_ai_answer`` → ``PW_AI_ANSWER`` (the ``<ENDPOINT>`` part
    of the knob names)."""
    p = path.strip("/")
    if p.startswith("v1/"):
        p = p[3:]
    return "".join(c if c.isalnum() else "_" for c in p).upper() or "ROOT"


class ExemplarHistogram:
    """Fixed-bucket histogram whose ``_bucket`` lines carry OpenMetrics
    exemplars: the last (trace_id, value, wall time) observed in each
    bucket.  One exemplar per bucket keeps the exposition bounded while
    still linking every latency regime — including the burning tail —
    to a concrete trace."""

    __slots__ = ("hist", "exemplars")

    def __init__(self, buckets: tuple[float, ...]):
        self.hist = Histogram(buckets)
        #: bucket index (incl. +Inf) -> (trace_id, value, wall_ts)
        self.exemplars: list[tuple[str, float, float] | None] = [None] * (
            len(buckets) + 1
        )

    def observe(self, value: float, trace_id: str | None) -> None:
        self.hist.observe(value)
        if trace_id:
            for i, le in enumerate(self.hist.buckets):
                if value <= le:
                    self.exemplars[i] = (trace_id, value, time.time())
                    return
            self.exemplars[-1] = (trace_id, value, time.time())

    def openmetrics_lines(self, family: str, labels: str) -> list[str]:
        base = self.hist.openmetrics_lines(family, labels)
        out = []
        bucket_i = 0
        for line in base:
            if line.startswith(f"{family}_bucket"):
                ex = self.exemplars[bucket_i]
                bucket_i += 1
                if ex is not None:
                    tid, val, ts = ex
                    line += (
                        f' # {{trace_id="{escape_label_value(tid)}"}} '
                        f"{val:.3f} {ts:.3f}"
                    )
            out.append(line)
        return out


class _Series:
    """One SLO-tracked series: the exemplar histogram plus the bounded
    sample ring burn rates are computed over.  Targets are read from the
    env once at series creation (``reset_slo()`` re-reads them)."""

    __slots__ = (
        "name", "kind", "p99_ms", "avail", "freshness_s",
        "hist", "ring", "lock",
    )

    def __init__(self, name: str, kind: str, ring: int):
        self.name = name
        self.kind = kind  # "endpoint" | "freshness"
        env = endpoint_env_key(name)
        if kind == "endpoint":
            self.p99_ms = _env_float(f"PATHWAY_SLO_{env}_P99_MS", 0.0)
            self.avail = _env_float(f"PATHWAY_SLO_{env}_AVAIL", 0.0)
            self.freshness_s = 0.0
        else:
            self.p99_ms = 0.0
            self.avail = 0.0
            self.freshness_s = _env_float("PATHWAY_SLO_FRESHNESS_S", 0.0)
        # endpoint series render their histogram (with exemplars) on
        # /status; freshness series feed ONLY the burn ring — the gauge
        # family pathway_freshness_seconds is the exported surface, so a
        # per-connector histogram here would be dead weight
        self.hist = (
            ExemplarHistogram(_LATENCY_BUCKETS_MS)
            if kind == "endpoint"
            else None
        )
        #: PER-SECOND aggregate buckets ``[second, n, slow_bad, unavail]``
        #: — NOT per-sample entries: at production QPS a per-sample ring
        #: holds seconds of history and silently collapses the slow
        #: window onto the fast one (a 25 s blip would then burn BOTH
        #: windows and page).  Per-second buckets make retention
        #: time-bounded regardless of rate: the default 8192 buckets
        #: cover ~2.3 h, comfortably past the 1 h slow window.
        self.ring: deque[list] = deque(maxlen=ring)
        self.lock = threading.Lock()

    def _append_locked(self, mono: float, slow_bad: bool, unavail: bool) -> None:
        sec = int(mono)
        if self.ring and self.ring[-1][0] >= sec:
            slot = self.ring[-1]
            slot[1] += 1
            slot[2] += int(slow_bad)
            slot[3] += int(unavail)
        else:
            self.ring.append([sec, 1, int(slow_bad), int(unavail)])

    # -- recording -------------------------------------------------------
    def observe(
        self,
        duration_ms: float,
        status: int | None,
        trace_id: str | None,
        now: float | None,
    ) -> None:
        mono = time.monotonic() if now is None else now
        slow_bad = self.p99_ms > 0.0 and duration_ms > self.p99_ms
        unavail = status is not None and status >= 500
        with self.lock:
            self.hist.observe(duration_ms, trace_id)
            self._append_locked(mono, slow_bad, unavail)

    def observe_lag(self, lag_s: float, now: float | None) -> None:
        mono = time.monotonic() if now is None else now
        stale = self.freshness_s > 0.0 and lag_s > self.freshness_s
        with self.lock:
            self._append_locked(mono, stale, False)

    # -- burn-rate math --------------------------------------------------
    def _window_burn(
        self, window_s: float, budget: float, field: int, now: float
    ) -> tuple[float, int]:
        """(burn rate, sample count) over the trailing ``window_s``
        (cost bounded by window seconds, not sample count)."""
        n = 0
        bad = 0
        for sec, cnt, bad_slow, bad_unavail in reversed(self.ring):
            if now - sec > window_s:
                break  # ring is append-ordered: everything older too
            n += cnt
            bad += (bad_slow, bad_unavail)[field]
        if n == 0:
            return 0.0, 0
        return (bad / n) / max(budget, 1e-9), n

    def evaluate(self, cfg: dict[str, float], now: float) -> dict[str, Any]:
        with self.lock:
            objectives: dict[str, Any] = {}
            if self.p99_ms > 0.0 or self.freshness_s > 0.0:
                fast, n_fast = self._window_burn(
                    cfg["fast_s"], _LATENCY_BUDGET, 0, now
                )
                slow, n_slow = self._window_burn(
                    cfg["slow_s"], _LATENCY_BUDGET, 0, now
                )
                key = "latency" if self.kind == "endpoint" else "freshness"
                target = (
                    {"p99_ms": self.p99_ms}
                    if self.kind == "endpoint"
                    else {"max_lag_s": self.freshness_s}
                )
                objectives[key] = {
                    **target,
                    "burn_fast": round(fast, 3),
                    "burn_slow": round(slow, 3),
                    "samples_fast": n_fast,
                    "samples_slow": n_slow,
                }
            if self.avail > 0.0:
                budget = max(1.0 - self.avail, 1e-9)
                fast, n_fast = self._window_burn(cfg["fast_s"], budget, 1, now)
                slow, n_slow = self._window_burn(cfg["slow_s"], budget, 1, now)
                objectives["availability"] = {
                    "target": self.avail,
                    "burn_fast": round(fast, 3),
                    "burn_slow": round(slow, 3),
                    "samples_fast": n_fast,
                    "samples_slow": n_slow,
                }
        verdict = "ok"
        for obj in objectives.values():
            verdict = _worse(
                verdict,
                _verdict(obj["burn_fast"], obj["burn_slow"], cfg),
            )
        out: dict[str, Any] = {"verdict": verdict}
        if objectives:
            out["objectives"] = objectives
        else:
            out["objectives"] = {}
            out["note"] = "no SLO target configured (PATHWAY_SLO_* knobs)"
        return out


_RANK = {"ok": 0, "warn": 1, "burning": 2}


def _worse(a: str, b: str) -> str:
    return a if _RANK[a] >= _RANK[b] else b


def _verdict(fast: float, slow: float, cfg: dict[str, float]) -> str:
    """Multi-window verdict (SRE workbook): page only when BOTH windows
    burn hot — the fast window gives response time, the slow window
    keeps a transient spike from paging and lets recovery show."""
    if fast >= cfg["burn_hot"] and slow >= cfg["burn_hot"]:
        return "burning"
    if (fast >= cfg["burn_warn"] and slow >= cfg["burn_warn"]) or max(
        fast, slow
    ) >= cfg["burn_hot"]:
        return "warn"
    return "ok"


# -- public burn math (the federation plane reuses the SAME semantics) ------
# One verdict implementation for the whole system: a fleet-level burn
# computed by the router (observability/federation.py) must agree with a
# replica's own verdict on identical inputs, or operators see the router
# and the replica disagree about the same incident.

#: fixed latency-objective budget (p99 target ⇒ 1% of requests may exceed)
LATENCY_BUDGET = _LATENCY_BUDGET


def burn_settings() -> dict[str, float]:
    """The live window/threshold knobs (PATHWAY_SLO_* env)."""
    return _settings()


def burn_verdict(
    fast: float, slow: float, cfg: dict[str, float] | None = None
) -> str:
    """Multi-window verdict from two burn rates (``ok``/``warn``/
    ``burning``) — exactly the per-replica rule."""
    return _verdict(fast, slow, cfg if cfg is not None else _settings())


def worse_verdict(a: str, b: str) -> str:
    """The more severe of two verdicts."""
    return _worse(a, b)


def latency_target_ms(path: str) -> float:
    """The configured p99 target for an endpoint path (0.0 = no target),
    read from ``PATHWAY_SLO_<ENDPOINT>_P99_MS`` exactly as a replica
    series would read it."""
    return _env_float(
        f"PATHWAY_SLO_{endpoint_env_key(path)}_P99_MS", 0.0
    )


# ---------------------------------------------------------------------------
# engine singleton
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_endpoints: dict[str, _Series] = {}
_freshness: dict[str, _Series] = {}


def _series(table: dict[str, _Series], name: str, kind: str) -> _Series:
    s = table.get(name)
    if s is not None:
        return s
    with _lock:
        s = table.get(name)
        if s is None:
            # cap INCLUDES the "other" overflow series: once 63 real
            # endpoints exist, the 64th distinct path creates "other"
            # and everything beyond lands there — total series <= 64
            if kind == "endpoint" and len(table) >= _MAX_ENDPOINTS - 1:
                name = "other"
                s = table.get(name)
                if s is not None:
                    return s
            s = table[name] = _Series(name, kind, int(_settings()["ring"]))
    _ensure_provider()
    return s


def observe_request(
    path: str,
    duration_ms: float,
    status: int | None = None,
    trace_id: str | None = None,
    now: float | None = None,
) -> None:
    """One finished HTTP request (called by the tracing middleware for
    every endpoint, sampled or not — SLOs observe latency, not traces).
    ``now`` (monotonic seconds) is a test hook."""
    _series(_endpoints, path, "endpoint").observe(
        duration_ms, status, trace_id, now
    )


def observe_freshness(
    connector: str, lag_s: float, now: float | None = None
) -> None:
    """One end-to-end ingest→queryable lag observation for a connector
    (fed by ``FreshnessTracker.note_indexed``)."""
    _series(_freshness, connector, "freshness").observe_lag(lag_s, now)


def slo_status(now: float | None = None) -> dict[str, Any] | None:
    """The ``"slo"`` block on ``/v1/health``: per-endpoint (and
    per-connector freshness) burn rates + verdicts, plus the worst
    verdict overall — what a router checks before placing load."""
    with _lock:
        endpoints = dict(_endpoints)
        freshness = dict(_freshness)
    if not endpoints and not freshness:
        return None
    cfg = _settings()
    mono = time.monotonic() if now is None else now
    out: dict[str, Any] = {
        "windows": {"fast_s": cfg["fast_s"], "slow_s": cfg["slow_s"]},
        "thresholds": {"hot": cfg["burn_hot"], "warn": cfg["burn_warn"]},
    }
    verdict = "ok"
    if endpoints:
        out["endpoints"] = {}
        for name in sorted(endpoints):
            ev = endpoints[name].evaluate(cfg, mono)
            out["endpoints"][name] = ev
            verdict = _worse(verdict, ev["verdict"])
    if freshness:
        out["freshness"] = {}
        for name in sorted(freshness):
            ev = freshness[name].evaluate(cfg, mono)
            out["freshness"][name] = ev
            verdict = _worse(verdict, ev["verdict"])
    out["verdict"] = verdict
    return out


def reset_slo() -> None:
    """Test isolation hook: drop every series (targets re-read from the
    env on the next observation)."""
    with _lock:
        _endpoints.clear()
        _freshness.clear()


# ---------------------------------------------------------------------------
# /status provider
# ---------------------------------------------------------------------------


class _SloMetricsProvider:
    """``pathway_endpoint_latency_ms{endpoint=}`` exemplar histograms +
    ``pathway_slo_burn_rate{slo=,window=}`` gauges."""

    def stats(self) -> dict:
        return slo_status() or {}

    def openmetrics_lines(self) -> list[str]:
        return slo_metrics_lines()


def slo_metrics_lines(now: float | None = None) -> list[str]:
    with _lock:
        endpoints = dict(_endpoints)
        freshness = dict(_freshness)
    lines: list[str] = []
    if endpoints:
        lines.append("# TYPE pathway_endpoint_latency_ms histogram")
        for name in sorted(endpoints):
            s = endpoints[name]
            with s.lock:
                lines.extend(
                    s.hist.openmetrics_lines(
                        "pathway_endpoint_latency_ms",
                        f'endpoint="{escape_label_value(name)}"',
                    )
                )
    cfg = _settings()
    mono = time.monotonic() if now is None else now
    burn_lines: list[str] = []
    for table in (endpoints, freshness):
        for name in sorted(table):
            ev = table[name].evaluate(cfg, mono)
            slo_label = (
                name if table is endpoints else f"freshness:{name}"
            )
            for obj_name, obj in ev["objectives"].items():
                base = (
                    f'slo="{escape_label_value(slo_label)}",objective="'
                    f'{escape_label_value(obj_name)}"'
                )
                burn_lines.append(
                    f'pathway_slo_burn_rate{{{base},window="fast"}} '
                    f'{obj["burn_fast"]}'
                )
                burn_lines.append(
                    f'pathway_slo_burn_rate{{{base},window="slow"}} '
                    f'{obj["burn_slow"]}'
                )
    if burn_lines:
        lines.append("# TYPE pathway_slo_burn_rate gauge")
        lines.extend(burn_lines)
    return lines


def _ensure_provider() -> None:
    from ..internals.monitoring import register_metrics_provider_once

    register_metrics_provider_once("slo", _SloMetricsProvider)
