"""On-demand device profiling behind ``/v1/debug/profile?ms=``.

The only device-time numbers used to come from offline benches; when a
serving replica misbehaves NOW, the operator needs a trace window from
the LIVE process.  ``capture(ms)``:

* on a real TPU (jax already imported, backend exposes a profiler):
  ``jax.profiler`` traces the window into a spool directory and the
  artifact (a zip of the trace dir, openable in TensorBoard/XProf /
  Perfetto) is served back;
* everywhere else: a pure flight-recorder fallback — the window's spans
  exported as Chrome-tracing/Perfetto JSON — so tier-1 exercises the
  whole handler path without jax profiling and a CPU smoke still gets a
  usable timeline.

Operational guardrails: SINGLE-FLIGHT (a second capture while one runs
gets 409 — two overlapping device traces corrupt each other), duration
capped at ``PATHWAY_PROFILE_MAX_MS`` (default 10 s — a forgotten
``ms=3600000`` must not pin the profiler for an hour), bounded spool
(``PATHWAY_PROFILE_KEEP`` newest artifacts, default 4), and a
``PATHWAY_PROFILE_DIR`` knob (``off`` disables the endpoint entirely;
default is a per-process tempdir).

Import discipline: stdlib + flight_recorder only; jax is touched solely
behind a ``sys.modules`` gate inside the capture body.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time
from typing import Any

from ..internals.config import env_int as _env_int

__all__ = [
    "ProfileInFlight",
    "ProfilerDisabled",
    "capture",
    "profile_dir",
    "profiler_stats",
]


class ProfileInFlight(RuntimeError):
    """A capture is already running (handler answers 409)."""


class ProfilerDisabled(RuntimeError):
    """``PATHWAY_PROFILE_DIR=off`` (handler answers 503)."""


def profile_dir() -> str | None:
    """Spool directory for capture artifacts; ``None`` when disabled."""
    raw = os.environ.get("PATHWAY_PROFILE_DIR", "").strip()
    if raw.lower() in ("off", "0", "none", "disabled"):
        return None
    if raw:
        return raw
    # ("pw_profiles", not the package name: the metrics registry lint
    # greps for pathway-prefixed literals)
    return os.path.join(tempfile.gettempdir(), f"pw_profiles_{os.getpid()}")


def max_ms() -> float:
    return float(max(1, _env_int("PATHWAY_PROFILE_MAX_MS", 10_000)))


def keep_artifacts() -> int:
    return max(1, _env_int("PATHWAY_PROFILE_KEEP", 4))


#: single-flight gate — two overlapping jax profiler sessions abort the
#: runtime, and two overlapping window exports would interleave spools
_capture_lock = threading.Lock()
_stats_lock = threading.Lock()
_stats = {"captures_total": 0, "last_kind": None, "last_size_bytes": 0}


def _jax_profiler_available() -> bool:
    """True only on a live non-CPU backend that is ALREADY imported —
    capture must never initialize a device runtime, and jax.profiler on
    the CPU backend produces empty traces at real cost."""
    if "jax" not in sys.modules:
        return False
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 — backend gone / not initialized
        return False


def _prune_spool(root: str, keep: int | None = None) -> None:
    if keep is None:
        keep = keep_artifacts()
    try:
        entries = sorted(
            (os.path.join(root, e) for e in os.listdir(root)),
            key=os.path.getmtime,
        )
    except OSError:
        return
    for path in entries[:-keep]:
        try:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
        except OSError:
            pass


def _zip_dir(src_dir: str, dest_zip_base: str) -> str:
    return shutil.make_archive(dest_zip_base, "zip", src_dir)


def capture(ms: float) -> dict[str, Any]:
    """Trace a ``ms``-long window and return the artifact description
    (``path``/``kind``/``size_bytes``/``duration_ms``).  Raises
    :class:`ProfileInFlight` when a capture is running and
    :class:`ProfilerDisabled` when the knob is off."""
    root = profile_dir()
    if root is None:
        raise ProfilerDisabled("profiling disabled (PATHWAY_PROFILE_DIR=off)")
    if not _capture_lock.acquire(blocking=False):
        raise ProfileInFlight("a profile capture is already running")
    try:
        ms = min(max(float(ms), 1.0), max_ms())
        os.makedirs(root, exist_ok=True)
        # prune BEFORE producing the new artifact: pruning after would
        # let capture B delete capture A's artifact while A's response
        # is still streaming it (KEEP=1 made the window certain) — at
        # capture start the previous artifact is still among the newest
        _prune_spool(root, keep=max(1, keep_artifacts() - 1))
        tag = f"profile_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}_{int(time.monotonic() * 1000) % 100000}"
        if _jax_profiler_available():
            artifact, kind = _capture_jax(root, tag, ms)
        else:
            artifact, kind = _capture_flight_recorder(root, tag, ms)
        size = os.path.getsize(artifact)
        with _stats_lock:
            _stats["captures_total"] += 1
            _stats["last_kind"] = kind
            _stats["last_size_bytes"] = int(size)
        return {
            "path": artifact,
            "kind": kind,
            "size_bytes": int(size),
            "duration_ms": ms,
        }
    finally:
        _capture_lock.release()


def _capture_jax(root: str, tag: str, ms: float) -> tuple[str, str]:
    import jax

    trace_dir = os.path.join(root, tag)
    jax.profiler.start_trace(trace_dir)
    try:
        time.sleep(ms / 1000.0)
    finally:
        jax.profiler.stop_trace()
    artifact = _zip_dir(trace_dir, os.path.join(root, tag))
    shutil.rmtree(trace_dir, ignore_errors=True)
    return artifact, "jax"


def _capture_flight_recorder(root: str, tag: str, ms: float) -> tuple[str, str]:
    """Off-TPU window: sleep through it and export every span that
    OVERLAPS it (ended inside or started inside) as Perfetto JSON."""
    from ..internals.flight_recorder import FlightRecorder, get_recorder

    t0 = time.time()
    time.sleep(ms / 1000.0)
    t1 = time.time()
    rec = get_recorder()
    # mark_read=False: this export is machinery, not an operator read —
    # it must not reset the ring's dropped-before-read watermark
    spans = [
        s
        for s in rec.spans(mark_read=False)
        if s.start_s <= t1 and s.start_s + s.duration_ms / 1000.0 >= t0
    ]
    doc = FlightRecorder.perfetto(spans)
    doc["pw_profile"] = {
        "window_start_s": t0,
        "window_end_s": t1,
        "spans": len(spans),
        "kind": "flight_recorder",
    }
    artifact = os.path.join(root, f"{tag}.json")
    with open(artifact, "w") as f:
        json.dump(doc, f)
    return artifact, "flight_recorder"


def profiler_stats() -> dict[str, Any]:
    with _stats_lock:
        snap = dict(_stats)
    snap["in_flight"] = _capture_lock.locked()
    snap["dir"] = profile_dir()
    snap["max_ms"] = max_ms()
    return snap
