"""Tiered vector index: HBM hot tier + host-RAM cold tier.

One device's HBM — even mesh-sharded (PR 8) and int8-quantized (PR 11) —
is still a hard ceiling on corpus size.  This package holds the tiering
layer above it: :class:`TieredKnnIndex` keeps a bounded HOT tier resident
in HBM behind the existing ``DeviceKnnIndex`` / ``ShardedKnnIndex``
machinery (any ``index_dtype``), the full corpus in a host-RAM f32
matrix, and routes each query's cold probe through the seeded
:class:`~pathway_tpu.ops.lsh.PartitionRouter` — a search is one HBM
brute-force tick plus a bounded host-side probe of the routed partitions,
merged into one top-k.  Access counts drive online promotions/demotions
scheduled as ``BULK_INGEST`` work items on the PR 7 runtime (no new
loops); PR 6 chunked snapshots cover both tiers plus the tier assignment
so a warm restart rebuilds the same placement bit-for-bit.

See README "Operations: tiered index" for the operator view.
"""

from .index import (
    TIER_PLACEMENT_KEY,
    TieredKnnIndex,
    tier_hot_rows_default,
    tier_migrate_batch_default,
    tier_probe_default,
    tiering_status,
)

__all__ = [
    "TIER_PLACEMENT_KEY",
    "TieredKnnIndex",
    "tier_hot_rows_default",
    "tier_migrate_batch_default",
    "tier_probe_default",
    "tiering_status",
]
