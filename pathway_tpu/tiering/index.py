"""Two-tier KNN index: HBM hot tier + routed host-RAM cold tier.

Design (ROADMAP item 1; EdgeRAG's prune-then-selectively-fetch and
VectorLiteRAG's partition-by-access-pattern, PAPERS.md):

* the **full corpus** lives in one host-RAM f32 matrix (the cold store —
  normalized rows, numpy); a seeded :class:`~pathway_tpu.ops.lsh
  .PartitionRouter` assigns every row to a partition at insert time;
* a bounded **hot tier** (``hot_rows`` rows) is additionally resident in
  HBM behind an ordinary :class:`~pathway_tpu.ops.knn.DeviceKnnIndex`
  (or a mesh-sharded :class:`~pathway_tpu.parallel.index.ShardedKnnIndex`
  — per-shard hot tiers) in any PR 11 ``index_dtype``, so the
  latency-critical slice keeps the one-matmul brute-force tick;
* a **search** is: one HBM brute-force tick over the hot tier, plus a
  device-side routing matmul picking ``probe_partitions`` cold
  partitions, plus a bounded host-side probe of those partitions; both
  candidate streams take their FINAL score from the host f32 mirror
  through one function (``ops/quantized_scoring.host_exact_scores``) and
  merge into one top-k — a key's score can never depend on which tier
  holds it, which is what makes online migration safe to interleave
  with serving;
* **access counts** accumulate per served key; once enough drift builds
  up, a promotion/demotion batch is scheduled as a ``BULK_INGEST``
  work item on the PR 7 :class:`DeviceTickRuntime` (no new loops) —
  promotions stage through the ordinary upsert scatters (landing via
  the PR 8 coalesced dropping-scatter path), demotions are tombstone
  flips, and every move happens under the index lock so a search never
  observes a half-migrated key;
* **snapshots**: the tier assignment (hot key set + router spec) rides
  the PR 6 snapshot plane as a reserved placement row plus the
  delta-chunk header, so a warm restart rebuilds the exact same
  placement with zero re-embeds (stdlib/indexing/lowering.py).
"""

from __future__ import annotations

import heapq
import itertools
import os
import threading
import time
import weakref
from typing import Any, Hashable, Sequence

import numpy as np

from ..ops.lsh import PartitionRouter
from ..ops.quantized_scoring import (
    dequantize_record,
    host_exact_scores,
    is_quant_record,
)

__all__ = [
    "TIER_PLACEMENT_KEY",
    "TieredKnnIndex",
    "tier_hot_rows_default",
    "tier_probe_default",
    "tier_migrate_batch_default",
    "tiering_status",
]

#: reserved snapshot-state key carrying the tier placement blob (hot key
#: set + router spec).  Rides the ordinary upsert delta stream — a plain
#: dict key the PR 6 framing needs no format bump for; readers that
#: predate tiering never see one because only tiered indexes write it.
#: stdlib/indexing/lowering.py pops it before feeding docs to the index.
TIER_PLACEMENT_KEY = "__pw_tier_placement__"


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def tier_hot_rows_default() -> int:
    """``PATHWAY_TIER_HOT_ROWS`` (default 0 = tiering off): HBM-resident
    row budget of the hot tier.  Any index factory built without an
    explicit ``hot_rows`` reads this — the process default reaches every
    server with zero plumbing, like ``PATHWAY_INDEX_DTYPE``."""
    try:
        n = int(os.environ.get("PATHWAY_TIER_HOT_ROWS", "0"))
    except ValueError:
        n = 0
    return max(n, 0)


def tier_probe_default() -> int:
    """``PATHWAY_TIER_PROBE_PARTITIONS`` (default 8): cold partitions
    probed per query.  Higher = better recall, more host bytes scanned;
    ``>= n_partitions`` makes the cold probe exhaustive (exact)."""
    try:
        n = int(os.environ.get("PATHWAY_TIER_PROBE_PARTITIONS", "8"))
    except ValueError:
        n = 8
    return max(n, 1)


def tier_migrate_batch_default() -> int:
    """``PATHWAY_TIER_MIGRATE_BATCH`` (default 256; 0 disables online
    migration): max rows moved per scheduled promotion/demotion item."""
    try:
        n = int(os.environ.get("PATHWAY_TIER_MIGRATE_BATCH", "256"))
    except ValueError:
        n = 256
    return max(n, 0)


class TieredKnnIndex:
    """Drop-in two-tier KNN index (module docstring).

    API-compatible with :class:`~pathway_tpu.ops.knn.DeviceKnnIndex` for
    everything the serving/ingest/recovery planes call: ``upsert`` /
    ``upsert_batch`` / ``upsert_coded`` / ``remove`` / ``search`` (host
    or device query batches, ``n_valid``) / ``rebuild_device_arrays`` /
    ``hbm_bytes`` / ``__len__``.
    """

    MIN_CAPACITY = 8

    def __init__(
        self,
        dim: int,
        hot_rows: int,
        metric: str = "cos",
        capacity: int = 1024,
        mesh: Any = None,
        index_dtype: str | None = None,
        n_partitions: int = 64,
        probe_partitions: int | None = None,
        migrate_batch: int | None = None,
        seed: int = 0,
    ):
        if metric not in ("cos", "l2sq", "dot"):
            raise ValueError(f"unknown metric {metric!r}")
        if hot_rows < 1:
            raise ValueError("TieredKnnIndex needs hot_rows >= 1 (0 = use "
                             "an untiered DeviceKnnIndex instead)")
        self.dim = int(dim)
        self.metric = metric
        self.hot_rows = int(hot_rows)
        self.probe_partitions = (
            int(probe_partitions)
            if probe_partitions is not None
            else tier_probe_default()
        )
        self.migrate_batch = (
            int(migrate_batch)
            if migrate_batch is not None
            else tier_migrate_batch_default()
        )
        self.router = PartitionRouter(dim, n_partitions=n_partitions, seed=seed)
        # hot tier: an ordinary device index (per-shard hot tiers when a
        # mesh is given) — its capacity is the hot budget, and the budget
        # is enforced HERE so the device index never grows past it
        if mesh is not None:
            from ..parallel.index import ShardedKnnIndex

            self.hot = ShardedKnnIndex(
                dim=dim, mesh=mesh, metric=metric, capacity=self.hot_rows,
                index_dtype=index_dtype,
            )
        else:
            from ..ops.knn import DeviceKnnIndex

            self.hot = DeviceKnnIndex(
                dim=dim, metric=metric, capacity=self.hot_rows,
                index_dtype=index_dtype,
            )
        self.hot.tier_role = "hot"
        self.index_dtype = self.hot.index_dtype
        # host-RAM cold store: every key's normalized f32 row (the hot
        # tier's rows included — host mirror of the whole corpus; the hot
        # fraction's duplication is bounded by hot_rows)
        self.capacity = max(int(capacity), self.MIN_CAPACITY)
        self._mat = np.zeros((self.capacity, self.dim), dtype=np.float32)
        self.slot_of_key: dict[Hashable, int] = {}
        self.key_of_slot: list[Hashable | None] = [None] * self.capacity
        self.free: list[int] = list(range(self.capacity - 1, -1, -1))
        # partition membership: live slots only (deletes remove the slot)
        self._parts: list[set[int]] = [
            set() for _ in range(self.router.n_partitions)
        ]
        self._part_cache: list[np.ndarray | None] = [None] * self.router.n_partitions
        self._part_of_slot = np.full((self.capacity,), -1, dtype=np.int32)
        # tier placement + access accounting
        self._hot_keys: set[Hashable] = set()
        self._hits: dict[Hashable, int] = {}
        self._hits_dirty = 0
        #: restore override: while set, upserts place per this key set
        #: instead of the fill rule (warm restart rebuilds placement
        #: bit-for-bit; cleared by finish_restore)
        self._forced_hot: set | None = None
        self._placement_rev = 0
        self._placement_dirty = False
        self._migration_pending = False
        self._lock = threading.RLock()
        # observability
        self.searches = 0
        self.probe_rows_total = 0
        self.migrations = {"promote": 0, "demote": 0}
        self.migrate_errors = 0
        self.rebuilds = 0
        self.tier_label = f"tiered{next(_tier_label_seq)}"
        self._migrate_group = None  # built lazily (runtime import)
        #: (trace_id, span_id) of the search that scheduled the pending
        #: migration — the migrate span links back to it
        self._migrate_trace_link: tuple[str, str] | None = None
        _LIVE_TIERED.add(self)
        _ensure_tier_provider()
        # unified HBM ledger: the hot tier registers itself through the
        # DeviceKnnIndex/ShardedKnnIndex constructor, so the ONLY
        # device-resident bytes still unaccounted here are the router's
        # centroid matrix (the [C, D] routing matmul operand)
        from ..observability.hbm_ledger import get_ledger

        get_ledger().register(
            f"tier_router:{self.tier_label}", self, _router_hbm_bytes
        )

    # -- sizing ----------------------------------------------------------
    def __len__(self) -> int:
        return len(self.slot_of_key)

    def hbm_bytes(self) -> int:
        """Device-resident bytes: the hot tier only — the whole point."""
        return self.hot.hbm_bytes()

    def host_bytes(self) -> int:
        """Host-RAM bytes of the cold store (the full-corpus mirror)."""
        return int(self._mat.nbytes + self._part_of_slot.nbytes)

    # NOTE: deliberately NO shard_row_counts passthrough — the restore
    # health path keys mesh fields off that attribute, and the hot
    # tier's per-shard counts would masquerade as the whole (restored)
    # corpus next to rows_restored.  Mesh shape rides the "tiering"
    # health block instead; the sharded hot tier reports its own rows
    # in the "mesh" block under role="hot".
    @property
    def n_shards(self) -> int:
        return getattr(self.hot, "n_shards", 1)

    # -- mutation --------------------------------------------------------
    def _grow_host(self) -> None:
        old = self.capacity
        self.capacity = old * 2
        self._mat = np.concatenate(
            [self._mat, np.zeros((old, self.dim), dtype=np.float32)]
        )
        self.key_of_slot.extend([None] * old)
        self.free.extend(range(self.capacity - 1, old - 1, -1))
        self._part_of_slot = np.concatenate(
            [self._part_of_slot, np.full((old,), -1, dtype=np.int32)]
        )

    def _normalize(self, vecs: np.ndarray) -> np.ndarray:
        v = np.asarray(vecs, dtype=np.float32)
        if self.metric != "cos":
            return v
        norms = np.linalg.norm(v, axis=1, keepdims=True)
        norms[norms == 0] = 1.0
        return v / norms

    def _want_hot_locked(self, key: Hashable) -> bool:
        if key in self._hot_keys:
            return True
        if self._forced_hot is not None:
            return key in self._forced_hot and len(self._hot_keys) < self.hot_rows
        return len(self._hot_keys) < self.hot_rows

    def _set_partition_locked(self, slot: int, part: int) -> None:
        old = int(self._part_of_slot[slot])
        if old == part:
            return
        if old >= 0:
            self._parts[old].discard(slot)
            self._part_cache[old] = None
        self._parts[part].add(slot)
        self._part_cache[part] = None
        self._part_of_slot[slot] = part

    def upsert(self, key: Hashable, vector: Any) -> None:
        vec = np.asarray(vector, dtype=np.float32).reshape(1, -1)
        if vec.shape[1] != self.dim:
            raise ValueError(
                f"vector dim {vec.shape[1]} != index dim {self.dim}"
            )
        self.upsert_batch([key], vec)

    def upsert_coded(self, key: Hashable, record: dict) -> None:
        """Quantized snapshot records (a dtype transition from an int8
        untiered index) dequantize once into the host store."""
        self.upsert(key, dequantize_record(record))

    def upsert_batch(self, keys: Sequence[Hashable], vectors) -> None:
        """Batch upsert.  ``vectors`` is ``[n, dim]`` host OR device
        array (``n >= len(keys)``; trailing rows are dispatch pads).
        The cold store is host RAM, so device batches pay one D2H here —
        the price of a corpus that does not fit HBM; hot-tier rows are
        re-staged to the device index from the host copy."""
        # np.asarray on a jax array is the D2H; pad rows sliced off first
        vecs = np.asarray(vectors, dtype=np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None, :]
        if vecs.shape[1] != self.dim:
            raise ValueError(
                f"vector batch shape {vecs.shape} != [n, {self.dim}]"
            )
        if vecs.shape[0] < len(keys):
            raise ValueError(
                f"{len(keys)} keys for {vecs.shape[0]} vector rows"
            )
        vecs = self._normalize(vecs[: len(keys)])
        parts = self.router.assign(vecs) if len(keys) else np.zeros((0,), np.int32)
        with self._lock:
            hot_keys: list[Hashable] = []
            hot_rows: list[int] = []
            for j, key in enumerate(keys):
                slot = self.slot_of_key.get(key)
                if slot is None:
                    if not self.free:
                        self._grow_host()
                    slot = self.free.pop()
                    self.slot_of_key[key] = slot
                    self.key_of_slot[slot] = key
                self._mat[slot] = vecs[j]
                self._set_partition_locked(slot, int(parts[j]))
                self._hits.setdefault(key, 0)
                if self._want_hot_locked(key):
                    if key not in self._hot_keys:
                        self._hot_keys.add(key)
                        self._placement_dirty = True
                        self._placement_rev += 1
                    hot_keys.append(key)
                    hot_rows.append(slot)
            if hot_keys:
                # last occurrence wins within the batch (the host matrix
                # already holds the final row per slot)
                self.hot.upsert_batch(hot_keys, self._mat[np.asarray(hot_rows)])

    def remove(self, key: Hashable) -> None:
        with self._lock:
            slot = self.slot_of_key.pop(key, None)
            if slot is None:
                return
            self.key_of_slot[slot] = None
            self.free.append(slot)
            part = int(self._part_of_slot[slot])
            if part >= 0:
                self._parts[part].discard(slot)
                self._part_cache[part] = None
                self._part_of_slot[slot] = -1
            self._hits.pop(key, None)
            if key in self._hot_keys:
                self._hot_keys.discard(key)
                self.hot.remove(key)
                self._placement_dirty = True
                self._placement_rev += 1

    # -- search ----------------------------------------------------------
    def _part_slots(self, part: int) -> np.ndarray:
        arr = self._part_cache[part]
        if arr is None:
            arr = np.fromiter(self._parts[part], dtype=np.int64, count=len(self._parts[part]))
            arr.sort()
            self._part_cache[part] = arr
        return arr

    def search(
        self, queries: Any, k: int, n_valid: int | None = None
    ) -> list[list[tuple[Hashable, float]]]:
        """Top-k per query as (key, score) lists, higher scores better.

        One hot-tier device tick (candidates), one device routing matmul,
        one bounded host probe of the routed partitions, one merged exact
        top-k from the host f32 mirror.  Deterministic: equal scores
        break ties by slot, so two processes with the same state answer
        bit-identically regardless of tier placement."""
        q = np.asarray(queries, dtype=np.float32)
        if q.ndim == 1:
            q = q[None, :]
        if n_valid is not None:
            q = q[: max(n_valid, 0)]
        n_q = q.shape[0]
        if n_q == 0:
            return []
        with self._lock:
            if not self.slot_of_key or k <= 0:
                return [[] for _ in range(n_q)]
            q = self._normalize(q)
            k_req = min(int(k), len(self.slot_of_key))
            # 1. hot tick: the HBM brute-force candidates.  The queries
            # are already L2-normalized above — `pre_normalized` keeps
            # the fused hot-tier kernel from normalizing a second time
            # (idempotent, but wasted FLOPs and a bf16 rounding
            # divergence risk; pinned by the normalize-once parity test)
            hot_res = (
                self.hot.search(q, k_req, pre_normalized=True)
                if len(self.hot)
                else [[] for _ in range(n_q)]
            )
            # 2. routing: device-side centroid scoring picks the cold
            # partitions each query probes
            routed = self.router.route(q, self.probe_partitions)
            out: list[list[tuple[Hashable, float]]] = []
            for qi in range(n_q):
                slot_arrs = [self._part_slots(int(p)) for p in routed[qi]]
                hot_slots = [
                    self.slot_of_key[key]
                    for key, _ in hot_res[qi]
                    if key in self.slot_of_key
                ]
                if hot_slots:
                    slot_arrs.append(np.asarray(hot_slots, dtype=np.int64))
                cand = (
                    np.unique(np.concatenate(slot_arrs))
                    if slot_arrs
                    else np.zeros((0,), np.int64)
                )
                if cand.size == 0:
                    out.append([])
                    continue
                self.probe_rows_total += int(cand.size)
                # 3. merge: ONE exact scoring of the union against the
                # host f32 mirror — tier-independent final scores
                scores = host_exact_scores(q[qi], self._mat[cand], self.metric)
                k_eff = min(k_req, cand.size)
                order = np.lexsort((cand, -scores))[:k_eff]
                row = []
                for i in order:
                    key = self.key_of_slot[int(cand[i])]
                    if key is None:
                        continue
                    row.append((key, float(scores[i])))
                    self._hits[key] = self._hits.get(key, 0) + 1
                out.append(row)
            self.searches += n_q
            self._hits_dirty += n_q
        self.maybe_schedule_migrations()
        return out

    # -- online tier migration ------------------------------------------
    def plan_migrations(
        self, limit: int | None = None
    ) -> tuple[list[Hashable], list[Hashable]]:
        """(promotions, demotions) by access count: top-hit cold keys
        fill free hot budget, then swap in over the least-hit hot keys
        they strictly out-hit.  Deterministic (ties break by slot)."""
        with self._lock:
            return self._plan_locked(limit)

    def _plan_locked(self, limit):
        limit = int(limit) if limit is not None else self.migrate_batch
        if limit <= 0:
            return [], []
        hits = self._hits
        slot = self.slot_of_key
        # at most ``limit`` cold keys are ever consumed (fill + swap), so
        # a bounded heap selection replaces a full O(n log n) sort of the
        # whole cold set — this runs under the index lock every
        # MIGRATE_CHECK_EVERY searches, and searches block on that lock
        cold = heapq.nsmallest(
            limit,
            (k for k in slot if k not in self._hot_keys),
            key=lambda k: (-hits.get(k, 0), slot[k]),
        )
        free = max(self.hot_rows - len(self._hot_keys), 0)
        promos = cold[: min(free, limit)]
        demos: list[Hashable] = []
        rest = cold[len(promos):]
        if rest and len(promos) < limit:
            hot_asc = heapq.nsmallest(
                limit, self._hot_keys, key=lambda k: (hits.get(k, 0), slot[k])
            )
            for ck, hk in zip(rest, hot_asc):
                if len(promos) >= limit:
                    break
                if hits.get(ck, 0) > hits.get(hk, 0):
                    promos.append(ck)
                    demos.append(hk)
                else:
                    break
        return promos, demos

    def migrate(
        self,
        plan: tuple[list[Hashable], list[Hashable]] | None = None,
        limit: int | None = None,
    ) -> dict:
        """Apply one promotion/demotion batch NOW (planning it first if
        ``plan`` is None).  Keys deleted since the plan was drawn are
        skipped — an in-flight migration of a removed key is a no-op,
        never a resurrection.  Runs under the index lock, so interleaved
        searches see either the old or the new placement, never half."""
        t0 = time.monotonic()
        wall = time.time()
        from ..testing import faults as _faults

        if _faults.enabled:
            try:
                _faults.perturb("tier.migrate")
            except _faults.FaultInjected:
                # chaos containment: a failed migration pass is absorbed
                # right here — placements stay exactly as they were (the
                # batch is all-or-nothing under the lock anyway), serving
                # never notices, and the next search window re-arms the
                # check via the cleared pending flag
                with self._lock:
                    self._migration_pending = False
                    self.migrate_errors += 1
                return {"promoted": 0, "demoted": 0}
        with self._lock:
            self._migration_pending = False
            self._hits_dirty = 0
            trace_link, self._migrate_trace_link = self._migrate_trace_link, None
            promos, demos = plan if plan is not None else self._plan_locked(limit)
            n_promoted = n_demoted = 0
            for key in demos:
                # re-validate: the key must still exist and still be hot
                if key in self.slot_of_key and key in self._hot_keys:
                    self.hot.remove(key)
                    self._hot_keys.discard(key)
                    n_demoted += 1
            up_keys: list[Hashable] = []
            up_slots: list[int] = []
            for key in promos:
                s = self.slot_of_key.get(key)
                if s is None or key in self._hot_keys:
                    continue
                if len(self._hot_keys) + len(up_keys) >= self.hot_rows:
                    break
                up_keys.append(key)
                up_slots.append(s)
            if up_keys:
                # promotions ride the ordinary staged scatter path (and
                # its apply-time coalescing) — bit-for-bit the same
                # arithmetic as a fresh ingest of these rows
                self.hot.upsert_batch(up_keys, self._mat[np.asarray(up_slots)])
                self._hot_keys.update(up_keys)
                n_promoted = len(up_keys)
            if n_promoted or n_demoted:
                self.migrations["promote"] += n_promoted
                self.migrations["demote"] += n_demoted
                self._placement_dirty = True
                self._placement_rev += 1
        try:
            from ..internals.flight_recorder import new_span_id, record_span

            lineage = {}
            if trace_link is not None:
                # link the background migration to the search that
                # triggered it — it shows up in that request's trace
                lineage = {
                    "trace_id": trace_link[0],
                    "span_id": new_span_id(),
                    "parent_id": trace_link[1],
                }
            record_span(
                f"tier:migrate:{self.tier_label}", "runtime", wall,
                (time.monotonic() - t0) * 1000.0,
                attrs={
                    "promoted": n_promoted,
                    "demoted": n_demoted,
                    "hot_rows": len(self._hot_keys),
                },
                **lineage,
            )
        except Exception:  # noqa: BLE001 — observability must never raise
            pass
        return {"promoted": n_promoted, "demoted": n_demoted}

    #: schedule a migration check once this many served queries have
    #: accumulated new hit counts
    MIGRATE_CHECK_EVERY = 16

    def maybe_schedule_migrations(self) -> bool:
        """Submit one promotion/demotion batch as a ``BULK_INGEST`` work
        item on the unified runtime (at most one in flight).  With the
        runtime disabled (``PATHWAY_RUNTIME=0``) the batch applies
        inline — either way, no new loop exists anywhere."""
        if self.migrate_batch <= 0:
            return False
        with self._lock:
            if self._migration_pending:
                return False
            if self._hits_dirty < self.MIGRATE_CHECK_EVERY:
                return False
            self._migration_pending = True
        try:
            from ..runtime import QoS, WorkGroup, get_runtime, runtime_enabled

            if not runtime_enabled():
                self.migrate()
                return True
            if self._migrate_group is None:
                self._migrate_group = WorkGroup(
                    f"tier-migrate:{self.tier_label}",
                    lambda payloads: [self.migrate() for _ in payloads],
                    max_batch=1,
                )
            from ..internals.flight_recorder import current_trace_link

            self._migrate_trace_link = current_trace_link()
            # defer=True: a search executing INSIDE a runtime tick must
            # enqueue the migration for a LATER BULK_INGEST tick, never
            # run it inline on the interactive tick's latency budget
            get_runtime().submit(
                self._migrate_group,
                None,
                qos=QoS.BULK_INGEST,
                tokens=max(self.migrate_batch, 1),
                coalesce_s=0.0,
                defer=True,
            )
            return True
        except Exception:  # noqa: BLE001 — tier maintenance is
            # best-effort: the triggering query's results are already
            # computed, and a transient fault in migrate()/the runtime
            # must not ride its error path.  The check counter re-arms
            # on the next search window.
            self._migration_pending = False
            self.migrate_errors += 1
            return False

    # -- snapshot / restore ---------------------------------------------
    def snapshot_header(self) -> dict:
        """Delta-chunk header: the routing state a restored process must
        rebuild verbatim (the router is a pure function of its spec)."""
        return {"router": self.router.spec()}

    def apply_snapshot_header(self, header: dict) -> None:
        spec = (header or {}).get("router")
        if spec:
            self._apply_router_spec(spec)

    def _apply_router_spec(self, spec: dict) -> None:
        with self._lock:
            if self.router.spec() == spec:
                return
            self.router = PartitionRouter.from_spec(spec)
            self._parts = [set() for _ in range(self.router.n_partitions)]
            self._part_cache = [None] * self.router.n_partitions
            self._part_of_slot.fill(-1)
            live = sorted(self.slot_of_key.values())
            if live:
                slots = np.asarray(live, dtype=np.int64)
                parts = self.router.assign(self._mat[slots])
                for s, p in zip(live, parts):
                    self._set_partition_locked(int(s), int(p))

    @property
    def placement_dirty(self) -> bool:
        """Non-consuming probe: tier assignment changed since the last
        staged placement blob.  The streaming driver polls this while
        sources are idle — an online migration driven purely by query
        traffic must still reach the snapshot plane, so the driver steps
        the engine once to let ``end_of_step`` stage and persist it."""
        return self._placement_dirty

    def placement_blob_if_dirty(self) -> dict | None:
        """The placement delta the snapshot plane stages when the tier
        assignment changed since the last one (lowering.end_of_step)."""
        with self._lock:
            if not self._placement_dirty:
                return None
            self._placement_dirty = False
            return self._placement_blob_locked()

    def placement_blob(self) -> dict:
        with self._lock:
            return self._placement_blob_locked()

    def _placement_blob_locked(self) -> dict:
        return {
            "rev": self._placement_rev,
            "router": self.router.spec(),
            # repr-sorted: deterministic bytes regardless of set order
            "hot_keys": sorted(self._hot_keys, key=repr),
        }

    def restore_placement(self, blob: dict) -> None:
        """Pin placement for a warm restart: called BEFORE the restored
        rows stream back in, so each arriving key lands straight in the
        tier it held when the snapshot was cut."""
        if not blob:
            return
        with self._lock:
            spec = blob.get("router")
            if spec:
                self._apply_router_spec(spec)
            forced = list(blob.get("hot_keys", ()))
            if len(forced) > self.hot_rows:
                # the budget shrank since the snapshot (operator lowered
                # PATHWAY_TIER_HOT_ROWS): truncate DETERMINISTICALLY —
                # set-iteration/arrival order would make two restores of
                # the same snapshot place different keys hot
                forced = sorted(forced, key=repr)[: self.hot_rows]
            self._forced_hot = set(forced)
            self._reconcile_placement_locked()

    def finish_restore(self) -> None:
        """End of the restore stream: stop pinning placement (new keys
        follow the ordinary fill rule) and mark the restored placement
        clean — it IS the durable one."""
        with self._lock:
            self._forced_hot = None
            self._placement_dirty = False

    def _reconcile_placement_locked(self) -> None:
        """Align already-present keys with the forced placement (restore
        over a non-empty index, e.g. replayed rows that arrived before
        the placement blob)."""
        if self._forced_hot is None:
            return
        for key in [k for k in self._hot_keys if k not in self._forced_hot]:
            self._hot_keys.discard(key)
            self.hot.remove(key)
        for key in sorted(self._forced_hot, key=repr):
            s = self.slot_of_key.get(key)
            if s is None or key in self._hot_keys:
                continue
            if len(self._hot_keys) >= self.hot_rows:
                break
            self.hot.upsert(key, self._mat[s])
            self._hot_keys.add(key)

    def placement_digest(self) -> str:
        """Stable digest of (router spec, hot key set) — the observable
        the soak harness compares across a SIGKILL restore."""
        import hashlib

        blob = self.placement_blob()
        h = hashlib.blake2b(digest_size=16)
        h.update(repr(blob["router"]).encode())
        for k in blob["hot_keys"]:
            h.update(repr(k).encode())
        return h.hexdigest()

    # -- fatal-device-fault recovery ------------------------------------
    def rebuild_device_arrays(self, vectors_by_key=None) -> bool:
        """Recreate the HOT tier's device arrays after a fatal device
        fault.  The cold store is host RAM and survives by construction;
        if the hot index's own rebuild fails, the tier is rebuilt from
        the host mirror (fresh arrays, same keys) — the tiered index
        never needs the snapshot-provider fallback."""
        with self._lock:
            ok = False
            try:
                ok = self.hot.rebuild_device_arrays()
            except Exception:  # noqa: BLE001 — fall through to host rebuild
                ok = False
            if not ok:
                self._rebuild_hot_from_host_locked()
            self.rebuilds += 1
            return True

    def _rebuild_hot_from_host_locked(self) -> None:
        # fresh inner index with the same configuration, refilled from
        # the host mirror (placement unchanged)
        cls = type(self.hot)
        kwargs = dict(
            dim=self.dim, metric=self.metric, capacity=self.hot_rows,
            index_dtype=self.index_dtype,
        )
        if hasattr(self.hot, "mesh"):
            kwargs["mesh"] = self.hot.mesh
        self.hot = cls(**kwargs)
        self.hot.tier_role = "hot"
        keys = [k for k in self._hot_keys if k in self.slot_of_key]
        if keys:
            slots = np.asarray([self.slot_of_key[k] for k in keys])
            self.hot.upsert_batch(keys, self._mat[slots])
        self._hot_keys = set(keys)


# ---------------------------------------------------------------------------
# tiering observability: pathway_tier_* series on /status, "tiering" block
# on /v1/health (internals/health.py reads tiering_status() only when this
# module is already imported — a health probe never pulls jax)
# ---------------------------------------------------------------------------

_LIVE_TIERED: "weakref.WeakSet[TieredKnnIndex]" = weakref.WeakSet()
_tier_label_seq = itertools.count()


def _router_hbm_bytes(idx: "TieredKnnIndex") -> int:
    """HBM ledger ``bytes_fn`` (module-level so the ledger's weak owner
    ref stays the only reference): the router's centroid matrix.  Reads
    ``idx.router`` at call time — a restore that swaps the router spec
    is tracked automatically."""
    return int(getattr(idx.router.centroids, "nbytes", 0))


def _live_tiered() -> list[TieredKnnIndex]:
    return sorted(_LIVE_TIERED, key=lambda i: i.tier_label)


class _TierMetricsProvider:
    """``pathway_tier_*`` OpenMetrics series over every live tiered
    index: per-tier row counts, migration counters, probe width."""

    def stats(self) -> dict:
        return tiering_status() or {}

    def openmetrics_lines(self) -> list[str]:
        from ..internals.metrics_names import escape_label_value

        indexes = _live_tiered()
        if not indexes:
            return []
        lines = ["# TYPE pathway_tier_rows gauge"]
        for idx in indexes:
            lbl = f'index="{escape_label_value(idx.tier_label)}"'
            hot = len(idx._hot_keys)
            lines.append(f'pathway_tier_rows{{{lbl},tier="hot"}} {hot}')
            lines.append(
                f'pathway_tier_rows{{{lbl},tier="cold"}} {len(idx) - hot}'
            )
        lines.append("# TYPE pathway_tier_migrations_total counter")
        for idx in indexes:
            lbl = f'index="{escape_label_value(idx.tier_label)}"'
            for direction in ("promote", "demote"):
                lines.append(
                    f'pathway_tier_migrations_total{{{lbl},direction="'
                    f'{direction}"}} {idx.migrations[direction]}'
                )
        lines.append("# TYPE pathway_tier_probe_partitions gauge")
        for idx in indexes:
            lbl = f'index="{escape_label_value(idx.tier_label)}"'
            lines.append(
                f"pathway_tier_probe_partitions{{{lbl}}} "
                f"{idx.probe_partitions}"
            )
        return lines


def _ensure_tier_provider() -> None:
    # once-registration with a strong ref held by monitoring (the
    # provider table itself is weak-valued)
    from ..internals.monitoring import register_metrics_provider_once

    register_metrics_provider_once("tiering", _TierMetricsProvider)


def tiering_status() -> dict | None:
    """Per-index tier state for ``/v1/health`` (None when no tiered
    index is live)."""
    indexes = _live_tiered()
    if not indexes:
        return None
    out = {}
    for idx in indexes:
        hot = len(idx._hot_keys)
        out[idx.tier_label] = {
            "metric": idx.metric,
            "dim": int(idx.dim),
            "hot_dtype": idx.index_dtype,
            "hot_rows_budget": int(idx.hot_rows),
            "hot_rows": hot,
            "cold_rows": len(idx) - hot,
            "n_partitions": int(idx.router.n_partitions),
            "probe_partitions": int(idx.probe_partitions),
            "migrate_batch": int(idx.migrate_batch),
            "migrations": dict(idx.migrations),
            "migrate_errors": int(idx.migrate_errors),
            "searches": int(idx.searches),
            "probe_rows_total": int(idx.probe_rows_total),
            "hbm_bytes": int(idx.hbm_bytes()),
            "host_bytes": int(idx.host_bytes()),
            "placement_rev": int(idx._placement_rev),
            "mesh_devices": int(idx.n_shards) if idx.n_shards > 1 else None,
        }
    return out
