"""Debug helpers: build tables from literals, run and inspect.

reference: python/pathway/debug/__init__.py (table_from_markdown:431,
compute_and_print:207, table_from_pandas, compute_and_print_update_stream:235)
and python/pathway/tests/utils.py assert_table_equality:544-556.
"""

from __future__ import annotations

import re
from typing import Any, Iterable

import pandas as pd

from ..internals import dtype as dt
from ..internals.engine import OutputNode, freeze_row
from ..internals.graph import Operator
from ..internals.keys import ref_scalar, unsafe_make_pointer
from ..internals.runtime import GraphRunner
from ..internals.schema import (
    ColumnSchema,
    SchemaMetaclass,
    _schema_from_columns,
    schema_from_pandas,
)
from ..internals.table import Table
from ..internals.universe import Universe
from ..internals.value import Json, Pointer

__all__ = [
    "table_from_markdown",
    "table_from_pandas",
    "table_from_rows",
    "table_to_pandas",
    "table_to_dicts",
    "compute_and_print",
    "compute_and_print_update_stream",
    "materialize",
    "assert_table_equality",
    "assert_table_equality_wo_index",
    "assert_table_equality_wo_types",
    "assert_table_equality_wo_index_wo_types",
    "parse_to_table",
]

_SPECIAL_COLS = ("__time__", "__diff__")

# Auto-generated row keys are salted per table so two literal tables never
# collide in a concat (explicit ids stay cross-table comparable on purpose —
# assert_table_equality relies on that, like the reference's debug tables).
import itertools as _itertools

_table_salt = _itertools.count()


def _parse_value(raw: str) -> Any:
    raw = raw.strip()
    if raw in ("", "None"):
        return None
    if raw == "True":
        return True
    if raw == "False":
        return False
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "'\"":
        return raw[1:-1]
    return raw


def table_from_markdown(
    txt: str,
    *,
    id_from: list[str] | None = None,
    schema: SchemaMetaclass | None = None,
    _stream: bool = False,
) -> Table:
    """Parse a markdown-style table (reference: debug/__init__.py:431).

    The optional first unnamed column carries explicit row ids; special
    columns ``__time__``/``__diff__`` build update streams.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ...   | name  | age
    ... 1 | alice | 30
    ... 2 | bob   | 25
    ... ''')
    >>> pw.debug.compute_and_print(t, include_id=False)
    name | age
    alice | 30
    bob | 25

    Update streams replay timestamped diffs (same explicit id = same row):

    >>> s = pw.debug.table_from_markdown('''
    ...   | v | __time__ | __diff__
    ... 1 | 5 | 2        | 1
    ... 1 | 5 | 4        | -1
    ... 2 | 7 | 4        | 1
    ... ''')
    >>> pw.debug.compute_and_print(s, include_id=False)
    v
    7
    """
    lines = [l for l in txt.splitlines() if l.strip() and not set(l.strip()) <= {"-", "|", " "}]
    header = lines[0]
    sep = "|"
    header_cells = [c.strip() for c in header.split(sep)]
    has_leading_id = header_cells[0] == ""
    names = [c for c in header_cells if c != ""]

    rows = []
    for line in lines[1:]:
        cells = [c.strip() for c in line.split(sep)]
        if has_leading_id:
            rid = cells[0]
            vals = cells[1 : 1 + len(names)]
        else:
            rid = None
            vals = cells[: len(names)]
        rows.append((rid, [_parse_value(v) for v in vals]))

    data_names = [n for n in names if n not in _SPECIAL_COLS]
    time_idx = names.index("__time__") if "__time__" in names else None
    diff_idx = names.index("__diff__") if "__diff__" in names else None
    data_idx = [i for i, n in enumerate(names) if n not in _SPECIAL_COLS]

    # dtype inference per column
    if schema is not None:
        out_schema = schema
    else:
        columns = {}
        for i, n in zip(data_idx, data_names):
            col_vals = [r[1][i] for r in rows]
            columns[n] = ColumnSchema(name=n, dtype=_infer_dtype(col_vals))
        out_schema = _schema_from_columns(columns)

    salt = next(_table_salt)
    entries = {}  # time -> [(key, values, diff)]
    for rownum, (rid, vals) in enumerate(rows):
        key = (
            unsafe_make_pointer(int(rid))
            if rid is not None
            else ref_scalar("__autogen__", salt, rownum)
        )
        if id_from is not None:
            key = ref_scalar(*[vals[names.index(c)] for c in id_from])
        t = vals[time_idx] if time_idx is not None else 0
        d = vals[diff_idx] if diff_idx is not None else 1
        values = tuple(_coerce(vals[i], out_schema[n].dtype) for i, n in zip(data_idx, data_names))
        entries.setdefault(t, []).append((key, values, d))

    if time_idx is None:
        op = Operator(
            "input",
            [],
            params=dict(
                rows=[(k, v) for k, v, _ in entries.get(0, [])],
                schema=out_schema,
            ),
        )
    else:
        op = Operator(
            "input",
            [],
            params=dict(rows=None, stream=entries, schema=out_schema),
        )
    return Table._new(op, out_schema, Universe())


parse_to_table = table_from_markdown


def _infer_dtype(vals: list) -> dt.DType:
    non_null = [v for v in vals if v is not None]
    types = {type(v) for v in non_null}
    if not non_null:
        return dt.ANY
    if types == {bool}:
        base = dt.BOOL
    elif types == {int}:
        base = dt.INT
    elif types <= {int, float}:
        base = dt.FLOAT
    elif types == {str}:
        base = dt.STR
    else:
        base = dt.ANY
    if len(non_null) != len(vals) and base is not dt.ANY:
        return dt.Optional(base)
    return base


def _coerce(v, dtype: dt.DType):
    if v is None:
        return None
    base = dt.unoptionalize(dtype)
    if base is dt.FLOAT and isinstance(v, int):
        return float(v)
    return v


def table_from_pandas(
    df: pd.DataFrame,
    *,
    id_from: list[str] | None = None,
    schema: SchemaMetaclass | None = None,
) -> Table:
    if schema is None:
        schema = schema_from_pandas(df, id_from=id_from)
    names = schema.column_names()
    rows = []
    for pos, (idx, row) in enumerate(df.iterrows()):
        if id_from is not None:
            key = ref_scalar(*[row[c] for c in id_from])
        elif isinstance(idx, int):
            key = unsafe_make_pointer(idx)
        else:
            key = ref_scalar(idx)
        values = tuple(_pd_value(row[n], schema[n].dtype) for n in names)
        rows.append((key, values))
    op = Operator("input", [], params=dict(rows=rows, schema=schema))
    return Table._new(op, schema, Universe())


def _pd_value(v, dtype):
    import numpy as np

    if v is None or (isinstance(v, float) and pd.isna(v)):
        return None
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    if isinstance(v, np.str_):
        return str(v)
    return _coerce(v, dtype)


def table_from_rows(
    schema: SchemaMetaclass,
    rows: list[tuple],
    unsafe_trusted_ids: bool = False,
    is_stream: bool = False,
) -> Table:
    """reference: debug/__init__.py table_from_rows; first element of each
    tuple may be the id when the schema has no primary key."""
    names = schema.column_names()
    pk = schema.primary_key_columns()
    salt = next(_table_salt)
    entries = {}  # time -> [(key, values, diff)]
    data_rows = []
    for rownum, r in enumerate(rows):
        if is_stream:
            *vals, t, d = r
        else:
            vals, t, d = list(r), 0, 1
        if pk:
            key = ref_scalar(*[vals[names.index(c)] for c in pk])
        else:
            key = ref_scalar("__autogen__", salt, rownum)
        entries.setdefault(t, []).append((key, tuple(vals), d))
        data_rows.append((key, tuple(vals)))
    if is_stream:
        op = Operator("input", [], params=dict(rows=None, stream=entries, schema=schema))
    else:
        op = Operator("input", [], params=dict(rows=data_rows, schema=schema))
    return Table._new(op, schema, Universe())


# ---------------------------------------------------------------------------
# running / materializing
# ---------------------------------------------------------------------------


def materialize(*tables: Table) -> list[OutputNode]:
    """Run the graph in batch mode and return OutputNodes per table."""
    outs = [OutputNode(name=f"debug_out") for _ in tables]
    runner = GraphRunner()
    engine = runner.build(list(zip(tables, outs)))
    _drive(engine, runner)
    return outs


def _drive(engine, runner):
    """Run to completion, handling both static and stream inputs."""
    # stream inputs were queued with their own times by the lowering
    engine.run_all()


def table_to_pandas(table: Table, include_id: bool = True) -> pd.DataFrame:
    (out,) = materialize(table)
    names = table.column_names()
    data = {n: [] for n in names}
    ids = []
    for key, row in sorted(out.current.items(), key=lambda kv: kv[0]):
        ids.append(key)
        for n, v in zip(names, row):
            data[n].append(v)
    df = pd.DataFrame(data, columns=list(names))
    if include_id:
        df.index = ids
    return df


def table_to_dicts(table: Table):
    (out,) = materialize(table)
    names = table.column_names()
    ids = list(out.current.keys())
    columns = {
        n: {k: row[i] for k, row in out.current.items()} for i, n in enumerate(names)
    }
    return ids, columns


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    **kwargs,
) -> None:
    """reference: debug/__init__.py:207"""
    (out,) = materialize(table)
    names = table.column_names()
    if include_id:
        rows = sorted(out.current.items(), key=lambda kv: kv[0])
    else:
        # value order: keys are hashes, so key order looks arbitrary —
        # doctests and humans want a stable, legible ordering
        try:
            rows = sorted(out.current.items(), key=lambda kv: kv[1])
        except (TypeError, ValueError):
            # mixed/unorderable cells (ndarray comparison raises
            # ValueError, not TypeError) — stable repr order
            rows = sorted(
                out.current.items(), key=lambda kv: tuple(map(repr, kv[1]))
            )
    if n_rows is not None:
        rows = rows[:n_rows]
    header = (["id"] if include_id else []) + list(names)
    print(" | ".join(header))
    for key, row in rows:
        cells = []
        if include_id:
            cells.append(_fmt(key, short_pointers))
        cells.extend(_fmt(v, short_pointers) for v in row)
        print(" | ".join(cells))


def compute_and_print_update_stream(
    table: Table, *, include_id: bool = True, short_pointers: bool = True, **kwargs
) -> None:
    """reference: debug/__init__.py:235"""
    (out,) = materialize(table)
    names = table.column_names()
    header = (["id"] if include_id else []) + list(names) + ["__time__", "__diff__"]
    print(" | ".join(header))
    for key, row, time, diff in out.history:
        cells = []
        if include_id:
            cells.append(_fmt(key, short_pointers))
        cells.extend(_fmt(v, short_pointers) for v in row)
        cells.append(str(time))
        cells.append(str(diff))
        print(" | ".join(cells))


def _fmt(v, short_pointers: bool) -> str:
    if isinstance(v, Pointer) and short_pointers:
        return f"^{v.value % 0xFFFFF:05X}..."
    # strings print bare, matching the reference's table rendering (its
    # doctests show `alice`, not `'alice'`)
    return str(v)


# ---------------------------------------------------------------------------
# equality asserts (reference: python/pathway/tests/utils.py:544-580)
# ---------------------------------------------------------------------------


def _snapshot(table: Table, out: OutputNode):
    return {key: freeze_row(row) for key, row in out.current.items()}


def _assert_equality(t1: Table, t2: Table, wo_index: bool, wo_types: bool):
    if not wo_types:
        d1 = {n: c for n, c in t1.schema.dtypes().items()}
        d2 = {n: c for n, c in t2.schema.dtypes().items()}
        assert list(d1.keys()) == list(d2.keys()), f"column sets differ: {list(d1)} vs {list(d2)}"
        for n in d1:
            assert _dtype_compatible(d1[n], d2[n]), (
                f"column {n!r} dtypes differ: {d1[n]!r} vs {d2[n]!r}"
            )
    else:
        assert list(t1.column_names()) == list(t2.column_names())
    out1, out2 = materialize(t1, t2)
    s1, s2 = _snapshot(t1, out1), _snapshot(t2, out2)
    if wo_index:
        m1 = sorted(s1.values(), key=repr)
        m2 = sorted(s2.values(), key=repr)
        assert m1 == m2, f"tables differ (ignoring ids):\n{m1}\nvs\n{m2}"
    else:
        assert s1 == s2, f"tables differ:\n{s1}\nvs\n{s2}"


def _dtype_compatible(a: dt.DType, b: dt.DType) -> bool:
    return a == b or a is dt.ANY or b is dt.ANY


def assert_table_equality(t1: Table, t2: Table) -> None:
    _assert_equality(t1, t2, wo_index=False, wo_types=False)


def assert_table_equality_wo_index(t1: Table, t2: Table) -> None:
    _assert_equality(t1, t2, wo_index=True, wo_types=False)


def assert_table_equality_wo_types(t1: Table, t2: Table) -> None:
    _assert_equality(t1, t2, wo_index=False, wo_types=True)


def assert_table_equality_wo_index_wo_types(t1: Table, t2: Table) -> None:
    _assert_equality(t1, t2, wo_index=True, wo_types=True)
